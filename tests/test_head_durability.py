"""Head-plane durability: GCS WAL + full-table snapshots, restart-and-
reattach, and whole-node-loss forensics.

Acceptance (ISSUE 14): a chaos-injected GCS SIGKILL at an arbitrary WAL
offset — no pre-exit snapshot flush — loses zero acknowledged durable-table
mutations after restart, a serve deployment under load keeps serving across
the restart with only typed errors, and a SIGKILLed *node*'s shipped WAL
tails still close its workers' timelines.
"""

import asyncio
import json
import os
import threading
import time
import types

import pytest


# --------------------------------------------------------------------------
# units: WAL codec, torn tail, compaction, offline forensics
# --------------------------------------------------------------------------

def test_wal_append_replay_roundtrip(tmp_path):
    from ray_tpu.core.gcs import wal as wal_mod

    base = str(tmp_path / "gcs_store.pkl.wal")
    w = wal_mod.GcsWal(base)
    w.open(0)
    for i in range(10):
        w.append("kv_put", {"ns": "t", "key": f"k{i}", "value": b"v" * i})
    w.close()
    recs = list(wal_mod.replay(base, 0))
    assert [seq for seq, _, _ in recs] == list(range(1, 11))
    assert all(op == "kv_put" for _, op, _ in recs)
    assert recs[3][2] == {"ns": "t", "key": "k3", "value": b"vvv"}
    # replay honors after_seq (snapshot coverage)
    assert [seq for seq, _, _ in wal_mod.replay(base, 7)] == [8, 9, 10]
    # a fresh writer resumes the sequence in the existing segment
    w2 = wal_mod.GcsWal(base)
    w2.open(10)
    w2.append("kv_del", {"ns": "t", "key": "k0"})
    w2.close()
    assert list(wal_mod.replay(base, 10))[0][:2] == (11, "kv_del")


def test_wal_torn_tail_tolerated(tmp_path):
    """A SIGKILL mid-append leaves a short or CRC-failing final record; the
    reader keeps the intact prefix and drops only the torn tail."""
    from ray_tpu.core.gcs import wal as wal_mod

    base = str(tmp_path / "s.wal")
    w = wal_mod.GcsWal(base)
    w.open(0)
    for i in range(5):
        w.append("kv_put", {"ns": "n", "key": str(i), "value": b"x"})
    w.close()
    (_, path), = wal_mod.list_segments(base)
    intact = os.path.getsize(path)
    # garbage appended after the last record (bad CRC): dropped
    with open(path, "ab") as f:
        f.write(b"\x40\x00\x00\x00\xde\xad\xbe\xefgarbage")
    assert len(wal_mod.read_segment(path)) == 5
    # record torn mid-payload: only that record is lost
    with open(path, "r+b") as f:
        f.truncate(intact - 3)
    assert len(wal_mod.read_segment(path)) == 4
    # torn mid-header
    with open(path, "r+b") as f:
        f.truncate(2)
    assert wal_mod.read_segment(path) == []


def test_wal_open_truncates_torn_tail_before_appending(tmp_path):
    """Crash mid-append, restart, append more: the torn tail must be
    truncated at open, or the post-restart records would sit BEHIND
    garbage and be invisible to every future replay."""
    from ray_tpu.core.gcs import wal as wal_mod

    base = str(tmp_path / "s.wal")
    w = wal_mod.GcsWal(base)
    w.open(0)
    for i in range(3):
        w.append("kv_put", {"ns": "n", "key": str(i), "value": b"x"})
    w.close()
    (_, path), = wal_mod.list_segments(base)
    with open(path, "r+b") as f:  # SIGKILL mid-record
        f.truncate(os.path.getsize(path) - 2)
    w2 = wal_mod.GcsWal(base)
    w2.open(2)  # restart replayed the 2 intact records
    w2.append("kv_put", {"ns": "n", "key": "post-crash", "value": b"y"})
    w2.close()
    recs = list(wal_mod.replay(base, 0))
    assert [r[0] for r in recs] == [1, 2, 3]
    assert recs[-1][2]["key"] == "post-crash"


def test_wal_compaction_rotate_prune(tmp_path):
    from ray_tpu.core.gcs import wal as wal_mod

    base = str(tmp_path / "s.wal")
    w = wal_mod.GcsWal(base)
    w.open(0)
    for i in range(6):
        w.append("kv_put", {"ns": "n", "key": str(i), "value": b"x"})
    sealed = w.rotate()
    assert sealed == 6
    w.append("kv_put", {"ns": "n", "key": "post", "value": b"y"})
    assert len(wal_mod.list_segments(base)) == 2
    # crash window: BOTH segments replay before the prune; seq filtering
    # makes the sealed one a no-op against a snapshot covering seq 6
    assert [seq for seq, _, _ in wal_mod.replay(base, sealed)] == [7]
    assert len(list(wal_mod.replay(base, 0))) == 7
    assert w.prune(sealed) == 1
    assert len(wal_mod.list_segments(base)) == 1
    assert [seq for seq, _, _ in wal_mod.replay(base, 0)] == [7]
    w.close()


def _mkconn():
    return types.SimpleNamespace()


def test_gcs_restore_snapshot_plus_wal(tmp_path):
    """In-process restart cycle: acknowledged mutations — including ones
    NEVER captured by any snapshot — survive via WAL replay; snapshot soft
    state (metrics ring, task events, shipped tails) restores; a dead
    node's shipped WAL tails close its timelines."""
    from ray_tpu.core.gcs.server import GcsServer

    store = str(tmp_path / "gcs_store.pkl")

    async def run():
        conn = _mkconn()
        g = GcsServer(store_path=store)
        await g.start()
        g.handle_kv_put(conn, "ns", "a", b"1")
        g.handle_register_function(conn, b"fid", b"blob")
        assert g.handle_register_driver(conn)["job_id"] == 1
        # idempotent re-register (driver reconnect): same id, no new mint
        assert g.handle_register_driver(conn, job_id=1)["job_id"] == 1
        assert g.job_counter == 1
        g.handle_register_channel_endpoint(
            conn, "chan1", {"host": "h", "port": 9, "node": "n"}, owner="n:1"
        )
        # unclean death: close the socket only — NO snapshot write
        await g.server.close()
        g.wal.close()

        g2 = GcsServer(store_path=store)
        await g2.start()
        assert g2.kv[("ns", "a")] == b"1"
        assert g2.functions[b"fid"] == b"blob"
        assert g2.job_counter == 1
        assert g2.channel_endpoints["chan1"]["endpoint"]["port"] == 9

        # snapshot carries the soft state; later WAL records layer on top
        g2.handle_ship_wal_tail(conn, "nodeX", {"wal-nodeX-7.jsonl": [
            {"task_id": "t1", "state": "RUNNING", "ts": 1.0, "name": "f"},
        ]})
        g2.timeseries.sample([{"name": "x", "kind": "counter",
                               "boundaries": [], "points": {(): 1.0}}])
        g2._write_snapshot()
        g2.handle_kv_put(conn, "ns", "c", b"3")
        await g2.server.close()
        g2.wal.close()

        g3 = GcsServer(store_path=store)
        await g3.start()
        assert g3.kv[("ns", "c")] == b"3" and g3.kv[("ns", "a")] == b"1"
        assert len(g3.timeseries) >= 1
        assert g3.node_wal_tails.get("nodeX")

        node = types.SimpleNamespace(node_id="nodeX", alive=True, conn=None)
        await g3._on_node_dead(node, "test")
        t = g3.task_events.get_task("t1")
        assert t is not None and t["state"] == "RUNNING"
        # idempotent: a second ingest of the same shipped tail dedups
        g3.task_events.ingest(
            [{"task_id": "t1", "state": "RUNNING", "ts": 1.0, "name": "f"}],
            source="wal-ship-nodeX-again",
        )
        assert len(g3.task_events.get_task("t1")["events"]) == 1
        await g3.server.close()
        g3.wal.close()

    asyncio.run(run())


def test_orphan_shipped_tails_ingest_after_restore(tmp_path):
    """A node that dies WHILE the GCS is down: only _on_node_dead ingests
    shipped tails, and a node that never re-registers is never declared
    dead "again" — the restore path must ingest its snapshot-restored
    tails after the re-register grace window so the dead workers' task
    timelines still close."""
    from ray_tpu.core.config import _config
    from ray_tpu.core.gcs.server import GcsServer

    store = str(tmp_path / "gcs_store.pkl")
    saved = _config.health_check_period_ms
    _config.health_check_period_ms = 100  # grace = max(2.0, 0.5) = 2s

    async def run():
        conn = _mkconn()
        g = GcsServer(store_path=store)
        await g.start()
        g.handle_ship_wal_tail(conn, "ghost", {"wal-ghost-1.jsonl": [
            {"task_id": "tg", "state": "EXECUTED", "ts": 1.0, "name": "f"},
        ]})
        g._write_snapshot()
        await g.server.close()
        g.wal.close()

        g2 = GcsServer(store_path=store)
        await g2.start()
        assert g2.node_wal_tails.get("ghost")
        # "ghost" never re-registers; past the grace window its tails are
        # ingested anyway and the timeline closes
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if g2.task_events.get_task("tg") is not None:
                break
            await asyncio.sleep(0.2)
        t = g2.task_events.get_task("tg")
        assert t is not None and t["state"] == "EXECUTED"
        assert "ghost" not in g2.node_wal_tails
        await g2.server.close()
        g2.wal.close()

    try:
        asyncio.run(run())
    finally:
        _config.health_check_period_ms = saved


def test_wal_disabled_restart_folds_leftover_segments(tmp_path):
    """`gcs_wal_enabled` toggled OFF across a restart: leftover segments
    are replayed (acked mutations survive the toggle), folded into a fresh
    snapshot, and deleted — so a later re-ENABLED restart can't replay the
    stale records over newer state (disabled-run snapshots carry wal_seq
    0, which would otherwise resurrect deleted keys)."""
    from ray_tpu.core.config import _config
    from ray_tpu.core.gcs import wal as wal_mod
    from ray_tpu.core.gcs.server import GcsServer

    store = str(tmp_path / "gcs_store.pkl")

    async def enabled_run():
        conn = _mkconn()
        g = GcsServer(store_path=store)
        await g.start()
        g.handle_kv_put(conn, "ns", "a", b"1")
        # unclean death: the mutation lives ONLY in the WAL
        await g.server.close()
        g.wal.close()

    asyncio.run(enabled_run())
    assert wal_mod.list_segments(store + ".wal")

    saved = _config.gcs_wal_enabled
    _config.gcs_wal_enabled = False

    async def disabled_run():
        conn = _mkconn()
        g = GcsServer(store_path=store)
        await g.start()
        assert g.kv[("ns", "a")] == b"1"  # folded from the leftover WAL
        assert not wal_mod.list_segments(store + ".wal")
        g.handle_kv_del(conn, "ns", "a")
        g._write_snapshot()  # the disabled plane's snapshot (wal_seq 0)
        await g.server.close()

    try:
        asyncio.run(disabled_run())
    finally:
        _config.gcs_wal_enabled = saved

    async def reenabled_run():
        g = GcsServer(store_path=store)
        await g.start()
        # the key deleted during the disabled run must NOT resurrect
        assert ("ns", "a") not in g.kv
        await g.server.close()
        g.wal.close()

    asyncio.run(reenabled_run())


def test_head_state_offline_forensics(tmp_path, capsys):
    """`scripts head-state` decodes snapshot + WAL with no running GCS."""
    from ray_tpu.core.gcs.server import GcsServer
    from ray_tpu import scripts

    store = str(tmp_path / "gcs_store.pkl")

    async def build():
        conn = _mkconn()
        g = GcsServer(store_path=store)
        await g.start()
        g.handle_kv_put(conn, "ns", "a", b"1")
        g._write_snapshot()
        g.handle_kv_put(conn, "ns", "b", b"2")
        g.handle_register_driver(conn)
        await g.server.close()
        g.wal.close()

    asyncio.run(build())
    rc = scripts.main(["head-state", "--store", str(tmp_path), "--json"])
    assert rc == 0
    state = json.loads(capsys.readouterr().out)
    assert state["snapshot_present"] is True
    assert set(state["kv_keys"]) == {"ns/a", "ns/b"}
    assert state["wal_records_replayed"] == 2  # kv_put b + job mint
    assert state["job_counter"] == 1
    # human-readable mode renders too
    assert scripts.main(["head-state", "--store", store]) == 0
    out = capsys.readouterr().out
    assert "kv keys:             2" in out


# --------------------------------------------------------------------------
# units: deadline clock-skew guard
# --------------------------------------------------------------------------

def test_effective_deadline_skew_guard():
    from ray_tpu.core import task_spec as ts

    # no mint info: pass through unchanged
    assert ts.effective_deadline(123.0, None, None) == 123.0
    assert ts.effective_deadline(None, 1.0, 1.0) is None

    # same boot (wall/mono offsets agree): exact monotonic elapsed — 1s of
    # a 2s budget spent, the localized deadline grants exactly 1s more
    d = ts.effective_deadline(1002.0, 1000.0, 50.0,
                              now_wall=1001.0, now_mono=51.0,
                              tolerance_s=5.0)
    assert abs(d - 1002.0) < 1e-9

    # same boot, wall clock STEPPED +100s mid-flight: mono still measures
    # the true 1s elapsed... but a 100s step breaks the offset match, so
    # the cross-host clamp re-anchors the remaining budget instead of
    # shedding a request that is 1s old
    d = ts.effective_deadline(1002.0, 1000.0, 50.0,
                              now_wall=1101.0, now_mono=51.0,
                              tolerance_s=5.0)
    assert d >= 1101.0  # never already-expired on a clock artifact

    # cross-host (incomparable monotonic), clocks within tolerance: the
    # minted wall deadline is used as-is (sheds stay exact)
    d = ts.effective_deadline(1002.0, 1000.0, 987654.0,
                              now_wall=1001.0, now_mono=3.0,
                              tolerance_s=5.0)
    assert d == 1002.0

    # cross-host, receiver 10s AHEAD (NTP skew beyond the 5s tolerance):
    # naive comparison would shed a fresh 2s-budget request instantly;
    # the guard clamps — full budget re-anchored on the receiver's clock
    d = ts.effective_deadline(1002.0, 1000.0, 987654.0,
                              now_wall=1010.0, now_mono=3.0,
                              tolerance_s=5.0)
    assert abs(d - 1012.0) < 1e-9

    # cross-host, receiver BEHIND: clamped the same way (no overstay past
    # the granted budget + tolerance)
    d = ts.effective_deadline(1002.0, 1000.0, 987654.0,
                              now_wall=990.0, now_mono=3.0,
                              tolerance_s=5.0)
    assert abs(d - 992.0) < 1e-9


def test_localize_deadline_one_shot():
    from ray_tpu.core import task_spec as ts
    from ray_tpu.core.ids import TaskID

    spec = ts.TaskSpec(
        task_id=TaskID.from_random(), name="t", fn_id=b"", args=[],
        kwargs={}, num_returns=1, resources={}, owner_addr="a",
        deadline=time.time() + 30.0,
    )
    spec.deadline_minted_wall = time.time()
    spec.deadline_minted_mono = time.monotonic()
    first = ts.localize_deadline(spec)
    assert first is not None and first == spec.deadline
    # second call is a no-op (already localized)
    assert ts.localize_deadline(spec) == first
    # specs without a deadline stay deadline-free
    spec2 = ts.TaskSpec(
        task_id=TaskID.from_random(), name="t", fn_id=b"", args=[],
        kwargs={}, num_returns=1, resources={}, owner_addr="a",
    )
    assert ts.localize_deadline(spec2) is None


# --------------------------------------------------------------------------
# unit: quantile sketches across the dashboard JSON boundary
# --------------------------------------------------------------------------

def test_sketches_cross_dashboard_json_boundary():
    """/api/timeseries carries each histogram's DDSketch JSON-safely, and
    samples_from_dashboard_json reconstructs it — dashboard-sourced
    percentiles match driver-side sketch math instead of bucket
    interpolation (the PR-13 gap)."""
    from ray_tpu.dashboard.app import timeseries_to_json
    from ray_tpu.scripts import samples_from_dashboard_json
    from ray_tpu.util import metrics as m

    s = m._Series("lat_ms", "histogram", "", boundaries=[1, 100, 10000])
    h = object.__new__(m.Histogram)
    h._tag_keys = ("deployment",)
    h._default_tags = {}
    h._series = s
    for v in (220, 230, 240, 250, 260, 270, 280, 290, 900, 990):
        h.observe(v, tags={"deployment": "d"})
    sample = {"ts": 12.0, "series": [s.snapshot()]}

    wire = json.loads(json.dumps(timeseries_to_json([sample])))
    back = samples_from_dashboard_json(wire)
    assert back[0]["series"][0].get("sketches"), "sketch dropped by JSON"

    p99_direct = m.window_percentile([sample], "lat_ms", 0.99,
                                     {"deployment": "d"})
    p99_wire = m.window_percentile(back, "lat_ms", 0.99, {"deployment": "d"})
    assert p99_wire == pytest.approx(p99_direct)
    # the sketch path is actually in effect: ±1% of the true p99 (990),
    # where bucket interpolation inside [100, 10000] could be off by ~9x
    assert abs(p99_wire - 990) / 990 < 0.02
    # without sketches the same JSON degrades to bucket interpolation —
    # proving the wire field is what carries the accuracy
    for x in wire[0]["series"]:
        x.pop("sketches", None)
    p99_stripped = m.window_percentile(
        samples_from_dashboard_json(wire), "lat_ms", 0.99,
        {"deployment": "d"})
    assert abs(p99_stripped - 990) / 990 > 0.05


# --------------------------------------------------------------------------
# cluster: acknowledged-mutation audit under a WAL-offset SIGKILL
# --------------------------------------------------------------------------

def _gcs_call(method, **kw):
    from ray_tpu.api import _global_worker

    core = _global_worker().backend.core

    async def call():
        return await core.gcs.call(method, timeout=30, **kw)

    return core.io.run(call(), timeout=60)


@pytest.mark.chaos(timeout=240)
def test_gcs_kill_at_wal_offset_loses_no_acked_mutations():
    """The acceptance audit: SIGKILL the GCS right after the Nth WAL record
    (no pre-exit flush — `_chaos_pre_exit` is retired), restart it, and
    every kv_put that was ACKNOWLEDGED must be present. Soft state (metrics
    ring samples, task history) recorded before the kill survives through
    the full-table snapshot."""
    import ray_tpu as ray
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.testing import chaos

    ray.shutdown()
    plan = chaos.plan(11).kill_gcs_at_wal(nth=12, match="kv_put")
    os.environ["RAY_TPU_GCS_SNAPSHOT_INTERVAL_S"] = "2"
    try:
        with plan:
            c = Cluster(head_node_args={"num_cpus": 2})
            ray.init(address=c.address)
    finally:
        os.environ.pop("RAY_TPU_GCS_SNAPSHOT_INTERVAL_S", None)
    try:
        # some task history + metrics ring samples, then outlive one
        # snapshot tick so the soft state is captured
        @ray.remote
        def f(x):
            return x + 1

        pre_kill_ref = f.remote(1)
        assert ray.get(pre_kill_ref, timeout=60) == 2
        time.sleep(4.0)
        t_kill = time.time()

        acked = []
        failed_key = None
        for i in range(40):
            key = f"k{i:02d}"
            try:
                assert _gcs_call("kv_put", ns="audit", key=key,
                                 value=str(i).encode())
                acked.append(key)
            except Exception:  # noqa: BLE001 - the injected crash
                failed_key = key
                break
        assert failed_key is not None, "chaos kill never fired"
        assert [e["point"] for e in plan.events()] == ["gcs.wal"]
        assert c.wait_gcs_exit(30), "GCS process must be dead"
        c.restart_gcs()

        # every ACKED mutation is back (reconnect window ridden out)
        deadline = time.time() + 60
        recovered = None
        while time.time() < deadline:
            try:
                recovered = {
                    k: _gcs_call("kv_get", ns="audit", key=k) for k in acked
                }
                break
            except Exception:  # noqa: BLE001 - reconnecting
                time.sleep(0.5)
        assert recovered is not None, "driver never reattached"
        missing = [k for k, v in recovered.items() if v is None]
        assert not missing, f"ACKNOWLEDGED mutations lost: {missing}"

        # snapshot soft state survived: pre-kill metric samples + the
        # pre-kill task's history are still there
        from ray_tpu.util import state

        samples = state.get_metrics_timeseries()
        assert any(s["ts"] < t_kill for s in samples), \
            "metrics ring lost across restart"
        t = state.get_task(pre_kill_ref.task_id.hex())
        assert t is not None and t["state"] == "FINISHED", t

        # and the cluster still runs fresh work
        assert ray.get(f.remote(5), timeout=60) == 6
    finally:
        ray.shutdown()
        c.shutdown()


# --------------------------------------------------------------------------
# cluster: serve keeps answering through a real GCS SIGKILL
# --------------------------------------------------------------------------

@pytest.mark.chaos(timeout=240)
def test_serve_keeps_answering_through_gcs_restart():
    """A serve deployment under continuous load rides out a hard GCS kill +
    restart: every request either succeeds or fails TYPED (RayTpuError),
    traffic succeeds both before and after the restart, and the fleet never
    stops answering for the whole window."""
    import ray_tpu as ray
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu import exceptions as exc

    ray.shutdown()
    c = Cluster(head_node_args={"num_cpus": 2})
    ray.init(address=c.address)
    try:
        @serve.deployment(name="echo")
        def echo(x):
            return x * 2

        handle = serve.run(echo)
        assert ray.get(handle.remote(3), timeout=60) == 6

        results = {"ok": 0, "typed": 0, "untyped": []}
        restarted = threading.Event()
        ok_after_restart = threading.Event()
        stop = threading.Event()

        def storm():
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    assert ray.get(handle.remote(i), timeout=10) == 2 * i
                    results["ok"] += 1
                    if restarted.is_set():
                        ok_after_restart.set()
                except exc.RayTpuError:
                    results["typed"] += 1
                except Exception as e:  # noqa: BLE001
                    results["untyped"].append(repr(e))
                time.sleep(0.01)

        t = threading.Thread(target=storm, daemon=True)
        t.start()
        time.sleep(1.5)
        assert results["ok"] > 0
        c.kill_gcs()          # real SIGKILL mid-storm, no flush
        time.sleep(1.0)
        c.restart_gcs()
        restarted.set()
        assert ok_after_restart.wait(30), (
            f"serve stopped answering after GCS restart: {results}"
        )
        time.sleep(2.0)
        stop.set()
        t.join(timeout=30)
        assert not results["untyped"], results["untyped"]
        assert results["ok"] > 20, results
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        ray.shutdown()
        c.shutdown()


# --------------------------------------------------------------------------
# cluster: whole-node SIGKILL → shipped WAL tails close the timeline
# --------------------------------------------------------------------------

@pytest.mark.chaos(timeout=240)
def test_node_loss_shipped_wal_closes_timeline():
    """Kill an entire node (raylet SIGKILL; its workers die with it). The
    dead workers' task-event WALs were shipped to the GCS beforehand, so
    the node death ingests them and the last task's worker-side states
    appear WITHOUT any same-host sweep (asserted well inside the sweep's
    60s floor) — the PR-8 'WAL recovery doesn't cover whole-node loss'
    gap."""
    import ray_tpu as ray
    from ray_tpu.cluster_utils import Cluster

    ray.shutdown()
    # workers flush every 60s -> their events live ONLY in the WAL; tails
    # ship every 300ms
    os.environ["RAY_TPU_TASK_EVENTS_FLUSH_INTERVAL_MS"] = "60000"
    os.environ["RAY_TPU_TASK_EVENTS_WAL_SHIP_INTERVAL_MS"] = "300"
    try:
        c = Cluster(head_node_args={"num_cpus": 1})
        victim = c.add_node(num_cpus=1, resources={"n2": 1})
        ray.init(address=c.address)
        try:
            c.wait_for_nodes(2)

            @ray.remote(resources={"n2": 0.5}, max_restarts=0)
            class Pinned:
                def work(self):
                    return os.getpid()

            a = Pinned.remote()
            ref = a.work.remote()
            ray.get(ref, timeout=60)
            time.sleep(1.5)  # >= a few ship ticks

            t_kill = time.monotonic()
            c.kill_node(victim)

            from ray_tpu.util import state

            deadline = time.monotonic() + 45
            states = []
            while time.monotonic() < deadline:
                t = state.get_task(ref.task_id.hex())
                states = [e["state"] for e in (t or {}).get("events", [])]
                if "EXECUTED" in states:
                    break
                time.sleep(0.5)
            elapsed = time.monotonic() - t_kill
            assert "EXECUTED" in states, (
                f"shipped WAL tail never closed the timeline: {states}"
            )
            assert elapsed < 45, elapsed
        finally:
            ray.shutdown()
            c.shutdown()
    finally:
        os.environ.pop("RAY_TPU_TASK_EVENTS_FLUSH_INTERVAL_MS", None)
        os.environ.pop("RAY_TPU_TASK_EVENTS_WAL_SHIP_INTERVAL_MS", None)


# --------------------------------------------------------------------------
# cluster: chaos plan propagation to already-running daemons
# --------------------------------------------------------------------------

@pytest.mark.chaos(timeout=180)
def test_chaos_activate_reaches_running_daemons():
    """chaos.activate pushes a plan over rpc to daemons that were ALREADY
    running when the plan was built (the env-var path can't reach them):
    the raylet fires a worker.lease kill and the task still completes via
    the owner's retry."""
    import ray_tpu as ray
    from ray_tpu.testing import chaos

    ray.shutdown()
    ray.init(num_cpus=2, num_tpus=0)  # NO plan active at spawn time
    try:
        plan = chaos.plan(5).kill_worker(after_tasks=1)
        n = chaos.activate(plan)
        assert n >= 2, f"GCS + raylet must accept the push, got {n}"

        @ray.remote
        def f(x):
            return x + 10

        assert ray.get(f.remote(1), timeout=120) == 11
        deadline = time.monotonic() + 30
        events = []
        while time.monotonic() < deadline:
            events = [e for e in plan.events()
                      if e["point"] == "worker.lease"]
            if events:
                break
            time.sleep(0.25)
        assert events, "pushed plan never fired in the raylet"
        assert events[0]["action"] == "kill"
        assert events[0]["pid"] != os.getpid(), "must fire in a daemon"

        # the counterpart: deactivate clears the driver env AND reaches
        # the same daemons, so a reused cluster stops firing
        n = chaos.deactivate()
        assert n >= 2, f"daemons must accept the deactivation, got {n}"
        assert chaos.ENV_PLAN not in os.environ
        assert chaos.active() is None
    finally:
        chaos.deactivate()
        ray.shutdown()


# --------------------------------------------------------------------------
# cluster: serve controller checkpoint restore (durable routing state)
# --------------------------------------------------------------------------

def test_serve_controller_checkpoint_restores_deployments():
    """The controller checkpoints its deployment targets into the durable
    GCS KV (which rides the WAL): after the controller actor is killed
    outright, a fresh serve.start() rebuilds the SAME deployments from the
    checkpoint and traffic flows again — no redeploy from the driver."""
    import ray_tpu as ray
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    ray.shutdown()
    ray.init(num_cpus=2, num_tpus=0)
    try:
        @serve.deployment(name="ckpt_echo")
        def echo(x):
            return x + 100

        handle = serve.run(echo)
        assert ray.get(handle.remote(1), timeout=60) == 101

        # kill the controller hard: its (owned) replicas die with it
        controller = ray.get_actor(serve_api.CONTROLLER_NAME)
        ray.kill(controller)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                ray.get_actor(serve_api.CONTROLLER_NAME)
                time.sleep(0.25)
            except Exception:  # noqa: BLE001 - controller gone
                break

        # a fresh attach (new driver semantics): controller restores the
        # checkpoint, reconcile restarts the replica fleet
        serve_api._local.clear()
        serve.start()
        deadline = time.monotonic() + 60
        value = None
        while time.monotonic() < deadline:
            try:
                h = serve.get_handle("ckpt_echo")
                value = ray.get(h.remote(2), timeout=10)
                break
            except Exception:  # noqa: BLE001 - fleet still rebuilding
                time.sleep(0.5)
        assert value == 102, (
            f"checkpointed deployment did not come back: {value!r} "
            f"(status={serve.status()})"
        )
    finally:
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001
            pass
        ray.shutdown()
