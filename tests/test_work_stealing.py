"""Pipelined-task work stealing (run-slot stealing gap, PR-13).

A task that blocks OUT-OF-BAND (plain sleep / rendezvous — it never enters
get_blocking, so it holds its run slot) used to pin every spec pipelined
behind it until worker_requeue_after_ms expired. With stealing, the owner
reclaims queued specs the moment another leased worker goes idle, so they
complete in milliseconds instead. The old ``worker_max_tasks_in_flight=1``
workaround is retired.
"""

import os
import time

import pytest

import ray_tpu

# the fallback requeue timer is pinned HIGH so only stealing can rescue the
# queued specs — the assertion below would fail on the timer alone
_REQUEUE_MS = "5000"


@pytest.fixture
def stealing_cluster():
    saved = os.environ.get("RAY_TPU_WORKER_REQUEUE_AFTER_MS")
    os.environ["RAY_TPU_WORKER_REQUEUE_AFTER_MS"] = _REQUEUE_MS
    from ray_tpu.core.config import _config

    saved_cfg = _config.worker_requeue_after_ms
    _config.worker_requeue_after_ms = int(_REQUEUE_MS)
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield
    ray_tpu.shutdown()
    if saved is None:
        os.environ.pop("RAY_TPU_WORKER_REQUEUE_AFTER_MS", None)
    else:
        os.environ["RAY_TPU_WORKER_REQUEUE_AFTER_MS"] = saved
    _config.worker_requeue_after_ms = saved_cfg


def test_spec_queued_behind_blocked_worker_migrates(stealing_cluster):
    """A spec committed to a busy worker completes on an idle one within
    bounded time: far sooner than the blocker finishes (2s) and far sooner
    than the requeue fallback (pinned at 5s)."""

    @ray_tpu.remote
    def blocker():
        time.sleep(2.0)  # out-of-band block: holds the run slot throughout
        return "blocked"

    @ray_tpu.remote
    def quick(i):
        return i

    # warm the 2-worker pool so placement (not process spawn) is measured
    ray_tpu.get([quick.remote(i) for i in range(8)], timeout=60)

    b = blocker.remote()
    time.sleep(0.1)  # the blocker takes its run slot
    t0 = time.perf_counter()
    # breadth-first placement stacks roughly half of these behind the
    # blocker; stealing migrates them to the idle worker
    out = ray_tpu.get([quick.remote(i) for i in range(12)], timeout=30)
    dt = time.perf_counter() - t0
    assert out == list(range(12))
    assert dt < 1.5, (
        f"quick tasks took {dt:.2f}s — queued specs were NOT stolen off "
        "the blocked worker (blocker=2s, requeue fallback=5s)"
    )
    assert ray_tpu.get(b, timeout=30) == "blocked"


def test_stealing_disabled_falls_back_to_requeue_timer(stealing_cluster):
    """With stealing off, the same shape stalls until the blocker ends or
    the requeue timer fires — the contrast that proves the steal (not
    placement luck) rescued the queued specs above."""
    from ray_tpu.core.config import _config

    os.environ["RAY_TPU_WORKER_STEALING_ENABLED"] = "0"
    saved = _config.worker_stealing_enabled
    _config.worker_stealing_enabled = False
    try:
        @ray_tpu.remote
        def blocker():
            time.sleep(1.2)
            return "blocked"

        @ray_tpu.remote
        def quick(i):
            return i

        ray_tpu.get([quick.remote(i) for i in range(8)], timeout=60)
        b = blocker.remote()
        time.sleep(0.1)
        t0 = time.perf_counter()
        out = ray_tpu.get([quick.remote(i) for i in range(12)], timeout=30)
        dt = time.perf_counter() - t0
        assert out == list(range(12))
        # the queued half waits out the blocker (requeue pinned at 5s)
        assert dt > 0.6, (
            f"drain took only {dt:.2f}s with stealing OFF — the test no "
            "longer queues specs behind the blocker, fix the shape"
        )
        assert ray_tpu.get(b, timeout=30) == "blocked"
    finally:
        os.environ.pop("RAY_TPU_WORKER_STEALING_ENABLED", None)
        _config.worker_stealing_enabled = saved
