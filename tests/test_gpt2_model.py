"""GPT-2 model + sharded train step on an 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt2
from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.train.train_step import (
    default_optimizer,
    make_gpt2_train_step,
    synthetic_batch,
)


def test_param_count_124m():
    cfg = gpt2.gpt2_124m()
    n = gpt2.param_count(cfg)
    # 124.4M with the standard vocab; padding to 50304 adds ~36k rows
    assert 123e6 < n < 126e6, n


def test_forward_shapes_and_finite():
    cfg = gpt2.gpt2_tiny()
    params = gpt2.init(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, cfg.seq_len), jnp.int32)
    logits = gpt2.forward(params, tokens, cfg)
    assert logits.shape == (2, cfg.seq_len, cfg.padded_vocab)
    assert bool(jnp.isfinite(logits.astype(jnp.float32)).all())


def test_causality():
    """Changing a future token must not affect earlier logits."""
    cfg = gpt2.gpt2_tiny(dtype=jnp.float32)
    params = gpt2.init(cfg, jax.random.PRNGKey(1))
    t1 = jnp.zeros((1, cfg.seq_len), jnp.int32)
    t2 = t1.at[0, -1].set(7)  # change only the last token
    l1 = gpt2.forward(params, t1, cfg)
    l2 = gpt2.forward(params, t2, cfg)
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)
    assert not np.allclose(l1[0, -1], l2[0, -1])


def test_chunked_loss_matches_monolithic():
    """The blockwise cross-entropy (loss_chunk) must equal the full-logits
    path: same loss value (both f32 softmax), gradients to within one bf16
    ulp (the fused monolithic path — ops/cross_entropy.py — recomputes the
    backward softmax from the saved logsumexp rather than a saved log-prob
    residual, so bf16-cast grads can differ in the last place)."""
    cfg_m = gpt2.gpt2_tiny(loss_chunk=0, seq_len=256)
    cfg_c = gpt2.gpt2_tiny(loss_chunk=64, seq_len=256)
    params = gpt2.init(cfg_m, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg_m.vocab_size, (2, 256)).astype(np.int32)
    tgt = np.roll(toks, -1, 1).copy()
    tgt[:, -1] = -1
    tgt[0, 5:9] = -1  # masked rows exercised
    l1, g1 = jax.value_and_grad(gpt2.loss_fn)(params, toks, tgt, cfg_m)
    l2, g2 = jax.value_and_grad(gpt2.loss_fn)(params, toks, tgt, cfg_c)
    assert float(abs(l1 - l2)) < 1e-5
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32), atol=1e-3
        )


def test_loss_decreases_single_device():
    cfg = gpt2.gpt2_tiny()
    bundle = make_gpt2_train_step(
        cfg,
        optimizer=default_optimizer(lr=1e-3, warmup=1, total_steps=50),
        rng=jax.random.PRNGKey(0),
    )
    batch = synthetic_batch(cfg, global_batch=4, seed=0)
    state = bundle.state
    losses = []
    for _ in range(8):
        state, metrics = bundle.step_fn(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize(
    "spec",
    [
        mesh_lib.MeshSpec(dp=8),
        mesh_lib.MeshSpec(fsdp=8),
        mesh_lib.MeshSpec(dp=2, fsdp=2, tp=2),
        mesh_lib.MeshSpec(fsdp=4, tp=2),
    ],
    ids=["dp8", "fsdp8", "dp2fsdp2tp2", "fsdp4tp2"],
)
def test_sharded_train_step_matches_meshes(spec, cpu_mesh8):
    """The same train step must run and give a finite loss under any mesh."""
    cfg = gpt2.gpt2_tiny()
    mesh = mesh_lib.make_mesh(spec, cpu_mesh8)
    bundle = make_gpt2_train_step(cfg, mesh=mesh, rng=jax.random.PRNGKey(0))
    batch = synthetic_batch(cfg, global_batch=8)
    state, metrics = bundle.step_fn(bundle.state, batch)
    assert np.isfinite(float(metrics["loss"]))
    assert int(jax.device_get(state["step"])) == 1


def test_dp_vs_single_device_loss_match(cpu_mesh8):
    """Data-parallel mesh must compute the same loss as one device (SPMD is a
    pure layout change)."""
    cfg = gpt2.gpt2_tiny(dtype=jnp.float32, param_dtype=jnp.float32)
    batch = synthetic_batch(cfg, global_batch=8)

    b1 = make_gpt2_train_step(cfg, rng=jax.random.PRNGKey(3))
    _, m1 = b1.step_fn(b1.state, batch)

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(dp=8), cpu_mesh8)
    b8 = make_gpt2_train_step(cfg, mesh=mesh, rng=jax.random.PRNGKey(3))
    _, m8 = b8.step_fn(b8.state, batch)

    np.testing.assert_allclose(
        float(m1["loss"]), float(m8["loss"]), rtol=2e-5
    )
