"""Dashboard HTTP API over GCS state (parity: dashboard/ head modules)."""

import json
import urllib.request

import pytest


@pytest.fixture
def cluster():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read()


def test_dashboard_serves_cluster_state(cluster):
    import time

    ray = cluster
    from ray_tpu.api import _global_worker
    from ray_tpu.dashboard import start_dashboard

    @ray.remote
    class Pinger:
        def ping(self):
            return "pong"

    p = Pinger.options(name="pinger").remote()
    assert ray.get(p.ping.remote(), timeout=60) == "pong"
    time.sleep(1.5)  # task-event flush

    gcs_address = _global_worker().backend.core.gcs_address
    dash = start_dashboard(gcs_address, port=0)
    try:
        nodes = json.loads(_get(dash.url + "/api/nodes"))
        assert any(n["Alive"] for n in nodes)

        actors = json.loads(_get(dash.url + "/api/actors"))
        assert any(a.get("name") == "pinger" for a in actors)

        tasks = json.loads(_get(dash.url + "/api/tasks"))
        assert any(t.get("name") == "ping" for t in tasks)

        clus = json.loads(_get(dash.url + "/api/cluster"))
        assert clus["total"].get("CPU", 0) >= 2

        page = _get(dash.url + "/").decode()
        assert "ray_tpu dashboard" in page
    finally:
        dash.stop()
