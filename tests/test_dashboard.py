"""Dashboard HTTP API over GCS state (parity: dashboard/ head modules)."""

import json
import urllib.request

import pytest


@pytest.fixture
def cluster():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read()


def test_dashboard_serves_cluster_state(cluster):
    import time

    ray = cluster
    from ray_tpu.api import _global_worker
    from ray_tpu.dashboard import start_dashboard

    @ray.remote
    class Pinger:
        def ping(self):
            return "pong"

    p = Pinger.options(name="pinger").remote()
    assert ray.get(p.ping.remote(), timeout=60) == "pong"
    time.sleep(1.5)  # task-event flush

    gcs_address = _global_worker().backend.core.gcs_address
    dash = start_dashboard(gcs_address, port=0)
    try:
        nodes = json.loads(_get(dash.url + "/api/nodes"))
        assert any(n["Alive"] for n in nodes)

        actors = json.loads(_get(dash.url + "/api/actors"))
        assert any(a.get("name") == "pinger" for a in actors)

        tasks = json.loads(_get(dash.url + "/api/tasks"))
        assert any(t.get("name") == "ping" for t in tasks)

        clus = json.loads(_get(dash.url + "/api/cluster"))
        assert clus["total"].get("CPU", 0) >= 2

        page = _get(dash.url + "/").decode()
        assert "ray_tpu dashboard" in page
    finally:
        dash.stop()


def test_dashboard_html_page(cluster):
    """The UI page itself (r3 verdict weak #8): correct content type, the
    table containers the refresh script fills, and the API routes it hits."""
    import time as _time

    ray = cluster
    from ray_tpu.api import _global_worker
    from ray_tpu.dashboard import start_dashboard

    gcs_address = _global_worker().backend.core.gcs_address
    dash = start_dashboard(gcs_address, port=0)
    try:
        import urllib.request

        with urllib.request.urlopen(dash.url + "/", timeout=30) as r:
            assert r.status == 200
            assert "text/html" in r.headers.get("Content-Type", "")
            page = r.read().decode()
        for marker in ('id="nodes"', 'id="actors"', 'id="tasks"',
                       "/api/cluster", "/api/nodes", "/api/actors",
                       "/api/tasks", "setInterval(refresh"):
            assert marker in page, marker
        # the prometheus endpoint rides the same server
        with urllib.request.urlopen(dash.url + "/metrics", timeout=30) as r:
            assert "text/plain" in r.headers.get("Content-Type", "")
    finally:
        dash.stop()
