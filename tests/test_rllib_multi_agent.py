"""Multi-agent RLlib: MultiAgentEnv, policy mapping, shared + independent
policies under PPO.

Parity: rllib/env/multi_agent_env.py + the policy_map/policy_mapping_fn
machinery of rollout workers; MultiAgentCartPole mirrors the reference's
example env.
"""

import numpy as np

from ray_tpu.rllib.algorithms import PPOConfig


def test_multi_agent_env_and_runner_mapping():
    """Env: dict-keyed per-agent arrays. Runner: each policy's batch holds
    exactly its mapped agents' rows (shared policy concatenates streams)."""
    from ray_tpu.rllib.env.multi_agent import MultiAgentCartPole
    from ray_tpu.rllib.multi_agent_runner import MultiAgentEnvRunner

    env = MultiAgentCartPole(num_agents=3, num_envs=4)
    obs = env.reset(seed=0)
    assert sorted(obs) == ["agent_0", "agent_1", "agent_2"]
    assert obs["agent_0"].shape == (4, env.obs_dim)
    o, r, te, tr = env.step({a: np.zeros(4, np.int64) for a in env.agent_ids})
    assert all(r[a].shape == (4,) for a in env.agent_ids)

    runner = MultiAgentEnvRunner(
        "MultiAgentCartPole",
        policy_mapping={"agent_0": "left", "agent_1": "left",
                        "agent_2": "right"},
        num_envs=4, hiddens=(16,), seed=0,
        env_kwargs={"num_agents": 3},
    )
    batches, metrics = runner.sample(16)
    assert sorted(batches) == ["left", "right"]
    # left serves two agents -> twice the rows of right
    assert len(batches["left"]) == 2 * len(batches["right"]) == 2 * 16 * 4
    assert "advantages" in batches["left"]
    # env-steps follow the single-agent contract (T ticks x N envs);
    # per-agent experience volume is a separate key
    assert metrics["num_env_steps"] == 16 * 4
    assert metrics["num_agent_steps"] == 16 * 4 * 3


def test_shared_policy_learns_multi_agent_cartpole():
    """config.multi_agent with ONE shared policy: both agents' streams train
    one policy and both agents' returns reach the target."""
    algo = (
        PPOConfig()
        .environment("MultiAgentCartPole", num_envs_per_worker=8,
                     env_kwargs={"num_agents": 2})
        .rollouts(num_rollout_workers=0, rollout_fragment_length=128)
        .multi_agent(policies=["shared"],
                     policy_mapping_fn=lambda aid: "shared")
        .training(lr=3e-4, num_epochs=8, minibatch_size=256)
        .debugging(seed=0)
        .build()
    )
    best = {}
    for i in range(60):
        res = algo.train()
        for aid, v in res.get("per_agent_reward_mean", {}).items():
            best[aid] = max(best.get(aid, -np.inf), v)
        if len(best) == 2 and min(best.values()) >= 150:
            break
    assert len(best) == 2 and min(best.values()) >= 150, best


def test_independent_policies_train_separately():
    """Two policies via mapping fn: each updates from its own agent's data
    (weights diverge) and both learn."""
    import jax

    algo = (
        PPOConfig()
        .environment("MultiAgentCartPole", num_envs_per_worker=8,
                     env_kwargs={"num_agents": 2})
        .rollouts(num_rollout_workers=0, rollout_fragment_length=128)
        .multi_agent(policies=["p0", "p1"],
                     policy_mapping_fn=lambda aid: "p" + aid[-1])
        .training(lr=3e-4, num_epochs=8, minibatch_size=256)
        .debugging(seed=0)
        .build()
    )
    assert algo.policy_mapping == {"agent_0": "p0", "agent_1": "p1"}
    best = {}
    for i in range(80):
        res = algo.train()
        for aid, v in res.get("per_agent_reward_mean", {}).items():
            best[aid] = max(best.get(aid, -np.inf), v)
        if len(best) == 2 and min(best.values()) >= 150:
            break
    assert len(best) == 2 and min(best.values()) >= 150, best
    w0 = algo._ma_weights["p0"]
    w1 = algo._ma_weights["p1"]
    diffs = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()), w0, w1
    ))
    assert max(diffs) > 1e-3  # trained on different data -> diverged


def test_multi_agent_checkpoint_roundtrip(tmp_path):
    """save_checkpoint/load_checkpoint carry every policy's learner state
    (the Algorithm base knows about multi-policy learner groups)."""
    import jax

    def build():
        return (
            PPOConfig()
            .environment("MultiAgentCartPole", num_envs_per_worker=4,
                         env_kwargs={"num_agents": 2})
            .rollouts(num_rollout_workers=0, rollout_fragment_length=32)
            .multi_agent(policies=["p0", "p1"],
                         policy_mapping_fn=lambda aid: "p" + aid[-1])
            .training(train_batch_size=256, num_epochs=2, minibatch_size=64)
            .debugging(seed=0)
            .build()
        )

    algo = build()
    algo.train()
    ckpt = algo.save_checkpoint(str(tmp_path))
    assert set(ckpt["learner_state"]) == {"p0", "p1"}

    algo2 = build()
    algo2.load_checkpoint(ckpt)
    for pid in ("p0", "p1"):
        diffs = jax.tree.leaves(jax.tree.map(
            lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
            algo.get_weights()[pid], algo2.get_weights()[pid],
        ))
        assert max(diffs) == 0.0, (pid, max(diffs))
    algo.cleanup()
    algo2.cleanup()
