"""Production SLO observability (PR 8): request-latency instrumentation,
metrics time series, and crash-safe trace forensics.

Parity targets: python/ray/_private/metrics_agent.py + prometheus_exporter
(exposition correctness), the dashboard's time-series charts (bounded
retention behind the /metrics snapshot), and the reference's task-event
durability gap (a SIGKILLed worker's unflushed TaskEventBuffer) closed here
with a per-worker WAL the raylet recovers.
"""

import json
import os
import re
import time
import urllib.request

import pytest

# ---------------------------------------------------------------- unit level


def _lint_prometheus(text: str) -> None:
    """Mini exposition-format lint: every histogram's buckets must be
    cumulative and non-decreasing in file order, the +Inf bucket must equal
    _count for the same tag set, and no raw (unescaped) newline may appear
    inside a label value (a quote-parity scan per line)."""
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        assert line.count('"') % 2 == 0, f"unbalanced quotes: {line!r}"
    buckets = {}
    counts = {}
    for line in text.splitlines():
        m = re.match(r"^(\w+)_bucket\{(.*)\}\s+(\S+)$", line)
        if m:
            name, tags, val = m.groups()
            le = re.search(r'le="([^"]*)"', tags).group(1)
            rest = re.sub(r',?le="[^"]*"', "", tags)
            buckets.setdefault((name, rest), []).append((le, float(val)))
            continue
        m = re.match(r"^(\w+)_count(?:\{(.*)\})?\s+(\S+)$", line)
        if m:
            name, tags, val = m.groups()
            counts[(name, tags or "")] = float(val)
    assert buckets, "no histogram buckets in exposition"
    for (name, tags), rows in buckets.items():
        vals = [v for _, v in rows]
        assert vals == sorted(vals), f"{name}{{{tags}}} not cumulative: {rows}"
        assert rows[-1][0] == "+Inf", f"{name}{{{tags}}} missing +Inf"
        assert rows[-1][1] == counts[(name, tags)], (
            f"{name}{{{tags}}}: +Inf {rows[-1][1]} != count "
            f"{counts[(name, tags)]}"
        )


def test_prometheus_tag_value_escaping():
    """Satellite: backslash, double quote and newline in tag values must be
    escaped per the text exposition format (previously interpolated raw,
    which corrupted every line after the first embedded newline)."""
    from ray_tpu.util.metrics import render_prometheus

    text = render_prometheus([
        {"name": "esc_total", "kind": "counter", "description": 'a\\b "c"\nd',
         "boundaries": [],
         "points": {(("route", 'x\\y"z"\nw'),): 2.0}},
    ])
    assert r'route="x\\y\"z\"\nw"' in text
    assert "# HELP esc_total a\\\\b \"c\"\\nd" in text
    # the rendered body must stay line-parseable
    for line in text.splitlines():
        assert line.count('"') % 2 == 0
    _ = _lint_prometheus  # escaping lint reused by the cluster test


def test_prometheus_histogram_exposition_lint():
    from ray_tpu.util.metrics import render_prometheus

    text = render_prometheus([
        {"name": "lat_ms", "kind": "histogram", "description": "lat",
         "boundaries": [1, 10],
         "points": {
             (("deployment", "A"),): [3, 2, 1, 25.0, 6],
             (("deployment", "B"),): [0, 0, 4, 400.0, 4],
         }},
    ])
    _lint_prometheus(text)
    assert 'lat_ms_bucket{deployment="A",le="+Inf"} 6' in text


def test_timeseries_ring_bounds_and_query():
    from ray_tpu.util.metrics import MetricsTimeSeries

    ts = MetricsTimeSeries(depth=5)
    for i in range(12):
        ts.sample(
            [{"name": "c", "kind": "counter", "description": "",
              "boundaries": [], "points": {(): float(i)}},
             {"name": "other", "kind": "gauge", "description": "",
              "boundaries": [], "points": {(): 1.0}}],
            ts=float(i),
        )
    assert len(ts) == 5  # bounded: oldest evicted
    samples = ts.query()
    assert [s["ts"] for s in samples] == [7.0, 8.0, 9.0, 10.0, 11.0]
    # name filter + limit
    filtered = ts.query(names=["c"], limit=2)
    assert len(filtered) == 2
    assert all(len(s["series"]) == 1 and s["series"][0]["name"] == "c"
               for s in filtered)


def test_rate_and_percentile_helpers():
    from ray_tpu.util.metrics import (
        counter_rate,
        histogram_percentile,
        window_percentile,
    )

    mk = lambda t, v: {
        "ts": t,
        "series": [{"name": "c", "kind": "counter", "description": "",
                    "boundaries": [], "points": {(): v}}],
    }
    assert counter_rate([mk(0, 0.0), mk(10, 50.0)], "c") == 5.0
    # counter reset (process restart) clamps to 0, never negative
    assert counter_rate([mk(0, 100.0), mk(10, 20.0)], "c") == 0.0
    assert counter_rate([mk(0, 1.0)], "c") is None  # one sample: no rate

    # percentile interpolates inside the winning bucket
    assert histogram_percentile([10, 100], [10, 0, 0], 0.5) == 5.0
    assert histogram_percentile([10, 100], [0, 10, 0], 1.0) == 100.0
    assert histogram_percentile([10, 100], [0, 0, 0], 0.5) is None

    # windowed percentile uses bucket DELTAS between first and last sample
    h = lambda t, pts: {
        "ts": t,
        "series": [{"name": "h", "kind": "histogram", "description": "",
                    "boundaries": [10, 100], "points": {(): pts}}],
    }
    samples = [h(0, [100, 0, 0, 100.0, 100]),   # history: all fast
               h(10, [100, 50, 0, 3000.0, 150])]  # window: 50 slow obs
    p = window_percentile(samples, "h", 0.5)
    assert p is not None and p > 10  # the window's median is in (10, 100]

    # tag filtering sums only matching points
    tagged = [{
        "ts": 0.0,
        "series": [{"name": "c", "kind": "counter", "description": "",
                    "boundaries": [],
                    "points": {(("deployment", "A"),): 1.0,
                               (("deployment", "B"),): 100.0}}],
    }, {
        "ts": 1.0,
        "series": [{"name": "c", "kind": "counter", "description": "",
                    "boundaries": [],
                    "points": {(("deployment", "A"),): 3.0,
                               (("deployment", "B"),): 100.0}}],
    }]
    assert counter_rate(tagged, "c", {"deployment": "A"}) == 2.0


def test_aggregator_per_job_retention():
    """Satellite: a chatty job evicts its OWN oldest tasks at the per-job
    cap; another job's history survives untouched."""
    from ray_tpu.tracing import TaskEventAggregator

    agg = TaskEventAggregator(max_tasks=1000, max_tasks_per_job=5)
    for i in range(20):
        agg.ingest([{"task_id": f"noisy-{i}", "name": "spam",
                     "state": "FINISHED", "ts": float(i), "job_id": "j1"}])
    for i in range(3):
        agg.ingest([{"task_id": f"quiet-{i}", "name": "rare",
                     "state": "FINISHED", "ts": 100.0 + i, "job_id": "j2"}])
    summary = agg.summarize()
    assert summary["tasks"]["spam"]["FINISHED"] == 5      # capped per job
    assert summary["tasks"]["rare"]["FINISHED"] == 3      # untouched
    assert summary["evicted_per_job"]["j1"] == 15
    assert agg.get_task("noisy-0") is None
    assert agg.get_task("noisy-19") is not None
    assert agg.get_task("quiet-0") is not None
    # jobless events still ride only the global cap
    agg.ingest([{"task_id": "nojob", "name": "x", "state": "FINISHED",
                 "ts": 1.0}])
    assert agg.get_task("nojob") is not None


def test_aggregator_derives_task_duration_histograms():
    """Core task latency series come from the lifecycle events already
    flowing into the aggregator — no new hot-path cost."""
    from ray_tpu.tracing import TaskEventAggregator
    from ray_tpu.util.metrics import get_registry

    agg = TaskEventAggregator(max_tasks=100)
    agg.ingest([
        {"task_id": "d1", "name": "dur_fn", "state": "SUBMITTED", "ts": 1.0},
        {"task_id": "d1", "name": "dur_fn", "state": "RUNNING", "ts": 1.1},
        {"task_id": "d1", "name": "dur_fn", "state": "EXECUTED", "ts": 1.3},
        {"task_id": "d1", "name": "dur_fn", "state": "FINISHED", "ts": 1.4},
    ])
    snaps = {s["name"]: s for s in get_registry().collect()}
    key = (("name", "dur_fn"),)
    e2e = snaps["task_e2e_ms"]["points"][key]
    ex = snaps["task_exec_ms"]["points"][key]
    assert e2e[-1] == 1 and abs(e2e[-2] - 400.0) < 1      # count, sum(ms)
    assert ex[-1] == 1 and abs(ex[-2] - 200.0) < 1


def test_wal_append_read_truncate(tmp_path):
    """The WAL holds every recorded event, tolerates a torn final line, and
    truncates once a flush drained the buffer (so recovery replays only the
    genuinely-unflushed tail)."""
    from ray_tpu.tracing import TaskEventBuffer, read_wal

    wal = str(tmp_path / "w.jsonl")
    buf = TaskEventBuffer(capacity=100)
    assert buf.enable_wal(wal)
    for i in range(4):
        buf.record(task_id=f"{i:032x}", name="t", state="RUNNING")
    events = read_wal(wal)
    assert [e["task_id"] for e in events] == [f"{i:032x}" for i in range(4)]
    assert all(e["state"] == "RUNNING" for e in events)

    # torn tail (SIGKILL mid-write): parse what's intact, skip the fragment
    with open(wal, "ab") as f:
        f.write(b'{"task_id": "fff')
    assert len(read_wal(wal)) == 4

    # flush drained the buffer -> WAL truncates to empty
    drained, _ = buf.drain()
    assert len(drained) == 4
    buf.wal_flushed()
    assert read_wal(wal) == []
    # and keeps working after truncation
    buf.record(task_id="a" * 32, name="t", state="FAILED")
    assert [e["state"] for e in read_wal(wal)] == ["FAILED"]

    # busy-worker path: events recorded AFTER the drain but before the
    # flush settles stay buffered — wal_flushed rewrites the file down to
    # exactly those, so the WAL never replays already-aggregated events
    buf.drain()
    buf.wal_flushed()
    buf.record(task_id="b" * 32, name="t", state="RUNNING")
    buf.drain()
    buf.record(task_id="c" * 32, name="t", state="RUNNING")  # post-drain
    buf.wal_flushed()  # buffer non-empty: rewrite, not skip
    assert [e["task_id"] for e in read_wal(wal)] == ["c" * 32]
    # appends continue on the re-opened file
    buf.record(task_id="d" * 32, name="t", state="EXECUTED")
    assert [e["task_id"] for e in read_wal(wal)] == ["c" * 32, "d" * 32]


# --------------------------------------------------------------- local level
def test_local_timeseries_history_and_state_helpers(ray_start_local):
    """Local-backend parity: the in-process sampler gives
    get_metrics_timeseries real history, and the rate/percentile helpers
    work against it (tier-1-testable retention layer)."""
    ray = ray_start_local
    from ray_tpu.core.config import _config
    from ray_tpu.util import state
    from ray_tpu.util.metrics import Counter, Histogram

    saved = _config.metrics_report_interval_ms
    _config.metrics_report_interval_ms = 100
    try:
        c = Counter("slo_local_total", tag_keys=("deployment",))
        h = Histogram("slo_local_ms", boundaries=[1, 10, 100],
                      tag_keys=("deployment",))
        tags = {"deployment": "L"}
        c.inc(3.0, tags)
        h.observe(5.0, tags)
        time.sleep(0.35)  # let the sampler take periodic samples
        c.inc(3.0, tags)
        h.observe(50.0, tags)
        samples = state.get_metrics_timeseries(names=["slo_local_total",
                                                      "slo_local_ms"])
        assert len(samples) >= 2  # periodic history, not just one snapshot
        assert samples[-1]["ts"] >= samples[0]["ts"]
        rate = state.metric_rate("slo_local_total", tags, samples=samples)
        assert rate is not None and rate > 0
        p99 = state.metric_percentile("slo_local_ms", 0.99, tags,
                                      samples=samples)
        p50 = state.metric_percentile("slo_local_ms", 0.5, tags,
                                      samples=samples)
        assert p50 is not None and p99 is not None and p50 <= p99
    finally:
        _config.metrics_report_interval_ms = saved


# ------------------------------------------------------------- cluster level
@pytest.fixture
def cluster():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def test_serve_slo_pipeline_cluster(cluster):
    """Acceptance: a cluster-mode serve request populates per-deployment
    e2e/queue/exec latency histograms visible on the dashboard /metrics
    endpoint AND in get_metrics_timeseries history; the exposition passes
    the format lint; rpc_* wire counters aggregate as real counters; task
    events carry the job id."""
    ray = cluster
    from ray_tpu import serve
    from ray_tpu.api import _global_worker
    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.util import state

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return x * 2

    try:
        handle = serve.run(Echo.bind())
        n = 8
        assert [ray.get(handle.remote(i), timeout=60) for i in range(n)] \
            == [i * 2 for i in range(n)]

        # replica registry flush (2s) + GCS sample loop (2s)
        gcs_addr = _global_worker().backend.core.gcs_address
        dash = start_dashboard(gcs_addr, port=0)
        deadline = time.monotonic() + 30
        text = ""
        want = ('serve_request_latency_ms_bucket{deployment="Echo"',
                'serve_exec_latency_ms_bucket{deployment="Echo"',
                'serve_queue_wait_ms_bucket{deployment="Echo"',
                'serve_requests_total{deployment="Echo"}')
        while time.monotonic() < deadline:
            with urllib.request.urlopen(dash.url + "/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            if all(w in text for w in want):
                break
            time.sleep(0.5)
        for w in want:
            assert w in text, f"missing {w!r} in /metrics:\n{text[:3000]}"
        m = re.search(r'serve_requests_total\{deployment="Echo"\} (\S+)',
                      text)
        assert m and float(m.group(1)) >= n
        # derived core-task series + cluster-wide rpc wire counters landed
        assert "task_e2e_ms_bucket" in text
        assert "# TYPE rpc_frames_sent counter" in text
        _lint_prometheus(text)

        # the same series are in the retained TIME SERIES, with history
        deadline = time.monotonic() + 20
        samples = []
        while time.monotonic() < deadline:
            samples = state.get_metrics_timeseries(
                names=["serve_requests_total", "serve_request_latency_ms",
                       "serve_exec_latency_ms"]
            )
            with_data = [s for s in samples if s["series"]]
            if len(with_data) >= 2:
                break
            time.sleep(0.5)
        assert len([s for s in samples if s["series"]]) >= 2
        tags = {"deployment": "Echo"}
        p50 = state.metric_percentile("serve_request_latency_ms", 0.5, tags,
                                      samples=samples)
        p99 = state.metric_percentile("serve_request_latency_ms", 0.99, tags,
                                      samples=samples)
        assert p50 is not None and p99 is not None and p50 <= p99

        # dashboard JSON timeseries + the top-like CLI rendering
        with urllib.request.urlopen(dash.url + "/api/timeseries?limit=10",
                                    timeout=10) as r:
            ts_json = json.loads(r.read())
        assert isinstance(ts_json, list) and ts_json
        assert any(x["name"] == "serve_requests_total"
                   for s in ts_json for x in s["series"])
        from ray_tpu.scripts import render_metrics_snapshot

        rendered = render_metrics_snapshot(state.get_metrics_timeseries())
        assert "Echo" in rendered and "qps" in rendered

        # `scripts metrics --dashboard`: the HTTP path renders the SAME
        # view from /api/timeseries with NO driver connection — the JSON
        # converter restores the internal tag-tuple point keys
        from ray_tpu.scripts import _fetch_timeseries_http

        http_samples = _fetch_timeseries_http(
            dash.url, limit=30
        )
        http_rendered = render_metrics_snapshot(http_samples)
        assert "Echo" in http_rendered and "qps" in http_rendered
        dash.stop()

        # per-job retention plumbing: task events carry the driver's job id
        rows = [r for r in state.list_tasks() if r["name"] == "handle_request"]
        assert rows
        t = state.get_task(rows[-1]["task_id"])
        assert any(e.get("job_id") for e in t["events"]), \
            "task events carry no job_id"
    finally:
        serve.shutdown()


@pytest.mark.chaos(timeout=180)
def test_wal_recovers_sigkilled_worker_events():
    """Acceptance (ROADMAP WAL item): a SIGKILLed worker's unflushed events
    are recovered from its WAL by the raylet and land in the aggregator —
    the killed task's timeline shows the worker-side RUNNING state and the
    previous call's profile span, and still terminates FAILED."""
    import ray_tpu
    from ray_tpu.testing import chaos
    from ray_tpu.util import state

    ray_tpu.shutdown()
    # workers flush every 60s -> every worker-side event of this test stays
    # unflushed and ONLY the WAL can deliver it. The driver keeps its normal
    # 1s flush (its _config predates the env var), so owner-side
    # SUBMITTED/FAILED still arrive on time.
    os.environ["RAY_TPU_TASK_EVENTS_FLUSH_INTERVAL_MS"] = "60000"
    try:
        with chaos.plan(seed=31).kill_actor(match="Victim.work",
                                            after_calls=2):
            ray_tpu.init(num_cpus=2, num_tpus=0)
            try:
                @ray_tpu.remote(max_restarts=0)
                class Victim:
                    def work(self):
                        from ray_tpu import tracing

                        with tracing.profile_span("last-breath"):
                            pass
                        return 1

                v = Victim.remote()
                assert ray_tpu.get(v.work.remote(), timeout=60) == 1
                dead_ref = v.work.remote()
                with pytest.raises(ray_tpu.exceptions.ActorDiedError):
                    ray_tpu.get(dead_ref, timeout=60)

                # WAL recovery is raylet-async (poll_deaths ~50ms + notify);
                # poll until the killed task's worker-side RUNNING appears
                deadline = time.monotonic() + 30
                states = []
                while time.monotonic() < deadline:
                    t = state.get_task(dead_ref.task_id.hex())
                    states = [e["state"] for e in (t or {}).get("events", [])]
                    if t and "RUNNING" in states and t["state"] == "FAILED":
                        break
                    time.sleep(0.5)
                assert t is not None and t["state"] == "FAILED", states
                assert "RUNNING" in states, (
                    f"worker-side RUNNING not recovered from WAL: {states}"
                )
                lifecycle = [s for s in states if s != "PROFILE"]
                assert lifecycle[-1] == "FAILED", lifecycle

                # call 1's span was also unflushed — recovered via the WAL
                spans = [
                    e for e in state.timeline_events()
                    if e.get("state") == "PROFILE"
                    and e.get("name") == "last-breath"
                ]
                assert spans, "profile span from the WAL never surfaced"
            finally:
                ray_tpu.shutdown()
    finally:
        os.environ.pop("RAY_TPU_TASK_EVENTS_FLUSH_INTERVAL_MS", None)


def test_samples_from_dashboard_json_roundtrip():
    """The /api/timeseries JSON shape (points as tag-dict lists) converts
    back into the internal sample shape the metrics math consumes: rates
    and histogram percentiles computed over HTTP-fetched samples match the
    driver-connection path."""
    from ray_tpu.scripts import samples_from_dashboard_json
    from ray_tpu.util.metrics import counter_rate, window_percentile

    data = [
        {
            "ts": 100.0,
            "series": [
                {"name": "serve_requests_total", "kind": "counter",
                 "boundaries": [],
                 "points": [{"tags": {"deployment": "d"}, "value": 10.0}]},
                {"name": "serve_request_latency_ms", "kind": "histogram",
                 "boundaries": [1.0, 10.0],
                 "points": [{"tags": {"deployment": "d"},
                             "value": [0.0, 0.0, 0.0, 0.0, 0.0]}]},
            ],
        },
        {
            "ts": 110.0,
            "series": [
                {"name": "serve_requests_total", "kind": "counter",
                 "boundaries": [],
                 "points": [{"tags": {"deployment": "d"}, "value": 30.0}]},
                {"name": "serve_request_latency_ms", "kind": "histogram",
                 "boundaries": [1.0, 10.0],
                 "points": [{"tags": {"deployment": "d"},
                             "value": [0.0, 20.0, 0.0, 110.0, 20.0]}]},
            ],
        },
    ]
    samples = samples_from_dashboard_json(data)
    assert samples[0]["series"][0]["points"] == {
        (("deployment", "d"),): 10.0
    }
    assert counter_rate(samples, "serve_requests_total",
                        {"deployment": "d"}) == pytest.approx(2.0)
    p50 = window_percentile(samples, "serve_request_latency_ms", 0.5,
                            {"deployment": "d"})
    assert p50 is not None and 1.0 <= p50 <= 10.0


def test_quantile_sketch_accuracy_and_merge():
    """PR-13: histograms carry a DDSketch-style quantile sketch beside the
    exposition buckets — tail percentiles come out within ~1% relative
    error instead of bucket interpolation (a p99 inside the 1000..2500ms
    bucket used to be anywhere in a 2.5x span)."""
    import random

    from ray_tpu.util import metrics as m

    h = m.Histogram("sketch_test_lat_ms", boundaries=[1, 10, 100, 1000],
                    tag_keys=("k",))
    rng = random.Random(7)
    vals = [rng.lognormvariate(3.0, 1.2) for _ in range(4000)]
    for v in vals:
        h.observe(v, {"k": "a"})
    snap = next(
        s for s in m.get_registry().collect()
        if s["name"] == "sketch_test_lat_ms"
    )
    assert "sketches" in snap
    sk = snap["sketches"][(("k", "a"),)]
    vals.sort()
    for q in (0.5, 0.9, 0.99):
        est = m.sketch_percentile(sk, q)
        true = vals[int(q * (len(vals) - 1))]
        assert abs(est - true) / true < 0.03, (q, est, true)
    # exposition buckets stay exact (the /metrics contract is unchanged):
    # bucket counts sum to the observation count
    pt = snap["points"][(("k", "a"),)]
    assert sum(pt[:-2]) == pt[-1] == len(vals)

    # merge: sketches sum bucket-wise across sources like histograms do
    import time as _t

    merged = m.merge_snapshots({
        "s1": (_t.time(), [snap]), "s2": (_t.time(), [snap]),
    })
    msnap = next(s for s in merged if s["name"] == "sketch_test_lat_ms")
    msk = msnap["sketches"][(("k", "a"),)]
    assert sum(msk["c"].values()) == 2 * sum(sk["c"].values())
    est = m.sketch_percentile(msk, 0.99)
    true = vals[int(0.99 * (len(vals) - 1))]
    assert abs(est - true) / true < 0.03  # merging two copies moves nothing


def test_window_percentile_prefers_sketch_and_falls_back():
    """window_percentile uses sketch deltas when present (accurate tails)
    and keeps the bucket-interpolation fallback for sketchless samples
    (e.g. series that crossed the dashboard's JSON boundary)."""
    from ray_tpu.util import metrics as m

    boundaries = [1, 10, 100, 1000]

    def series(count_hi, sketch):
        # one point: `count_hi` observations in the 100..1000 bucket
        s = {"name": "wp_sketch_test", "kind": "histogram",
             "boundaries": boundaries,
             "points": {(): [0, 0, 0, count_hi, 0, 0.0, count_hi]}}
        if sketch is not None:
            s["sketches"] = {(): sketch}
        return s

    def sk_of(values):
        sk = {"z": 0, "c": {}}
        for v in values:
            idx = m._sketch_index(v)
            sk["c"][idx] = sk["c"].get(idx, 0) + 1
        return sk

    first = {"ts": 100.0, "series": [series(10, sk_of([500.0] * 10))]}
    last = {"ts": 110.0, "series": [
        series(30, sk_of([500.0] * 10 + [880.0] * 20))
    ]}
    p = m.window_percentile([first, last], "wp_sketch_test", 0.5)
    # the WINDOW saw only the 880ms observations: the sketch knows that
    # within 1%, bucket interpolation could only say "100..1000"
    assert p is not None and abs(p - 880.0) / 880.0 < 0.02, p

    # sketchless fallback: same samples without sketches interpolate
    first_nb = {"ts": 100.0, "series": [series(10, None)]}
    last_nb = {"ts": 110.0, "series": [series(30, None)]}
    p2 = m.window_percentile([first_nb, last_nb], "wp_sketch_test", 0.5)
    assert p2 is not None and 100.0 <= p2 <= 1000.0
