"""Observability plane: metrics (Counter/Gauge/Histogram → /metrics) and
log streaming (worker print → driver stderr).

Parity targets: python/ray/util/metrics.py + _private/metrics_agent.py →
prometheus (the metrics API and exposition), python/ray/_private/
log_monitor.py (worker logs reach the driver).
"""

import re
import time
import urllib.request

import pytest

from ray_tpu.util.metrics import (
    Counter,
    Gauge,
    Histogram,
    get_registry,
    merge_snapshots,
    render_prometheus,
)


# --------------------------------------------------------------- unit level
def test_counter_gauge_histogram_collect():
    c = Counter("t_requests", description="req", tag_keys=("route",))
    c.inc(2.0, tags={"route": "/a"})
    c.inc(1.0, tags={"route": "/a"})
    c.inc(5.0, tags={"route": "/b"})
    g = Gauge("t_qsize")
    g.set(3)
    g.set(7)
    h = Histogram("t_latency", boundaries=[1, 10])
    h.observe(0.5)
    h.observe(5)
    h.observe(50)

    snaps = {s["name"]: s for s in get_registry().collect()}
    assert snaps["t_requests"]["points"][(("route", "/a"),)] == 3.0
    assert snaps["t_requests"]["points"][(("route", "/b"),)] == 5.0
    assert snaps["t_qsize"]["points"][()] == 7.0
    hp = snaps["t_latency"]["points"][()]
    assert hp[:3] == [1, 1, 1] and hp[-2] == 55.5 and hp[-1] == 3


def test_counter_rejects_negative_and_undeclared_tags():
    c = Counter("t_neg", tag_keys=("k",))
    with pytest.raises(ValueError):
        c.inc(-1)
    with pytest.raises(ValueError):
        c.inc(1, tags={"other": "x"})
    with pytest.raises(ValueError):
        Gauge("t_neg")  # name already registered as counter


def test_merge_and_render():
    now = time.time()
    mk = lambda kind, pts, **kw: {
        "name": "m", "kind": kind, "description": "d",
        "boundaries": kw.get("boundaries", []), "points": pts,
    }
    # counters sum across sources; gauges get a source label
    merged = merge_snapshots({
        "w1": (now, [mk("counter", {(): 2.0})]),
        "w2": (now, [mk("counter", {(): 3.0})]),
        "stale": (now - 1e6, [mk("counter", {(): 100.0})]),
    })
    assert merged[0]["points"][()] == 5.0
    merged_g = merge_snapshots({
        "w1": (now, [mk("gauge", {(): 1.0})]),
        "w2": (now, [mk("gauge", {(): 2.0})]),
    })
    assert len(merged_g[0]["points"]) == 2

    text = render_prometheus([
        {"name": "app_lat", "kind": "histogram", "description": "lat",
         "boundaries": [1, 10], "points": {(): [1, 2, 3, 55.5, 6]}},
        {"name": "app_req", "kind": "counter", "description": "",
         "points": {(("route", "/a"),): 3.0}, "boundaries": []},
    ])
    assert '# TYPE app_lat histogram' in text
    assert 'app_lat_bucket{le="1"} 1' in text
    assert 'app_lat_bucket{le="10"} 3' in text
    assert 'app_lat_bucket{le="+Inf"} 6' in text
    assert 'app_lat_sum 55.5' in text and 'app_lat_count 6' in text
    assert 'app_req{route="/a"} 3.0' in text


# ---------------------------------------------------------- cluster level
def test_worker_print_reaches_driver_and_metrics_export(capfd):
    """A print() inside a remote task must appear on the driver (the
    log-monitor → GCS pubsub → driver path), and metrics recorded in a
    worker must show up on the dashboard's Prometheus /metrics endpoint."""
    import ray_tpu
    from ray_tpu.dashboard import start_dashboard

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    try:
        @ray_tpu.remote
        def noisy(x):
            from ray_tpu.util.metrics import Counter

            print(f"hello-from-worker-{x}")
            Counter("t_worker_tasks", description="tasks run").inc()
            return x

        assert ray_tpu.get([noisy.remote(i) for i in range(3)]) == [0, 1, 2]

        # log lines flow: raylet tail (250ms) -> GCS -> driver push
        deadline = time.monotonic() + 15
        seen = ""
        while time.monotonic() < deadline:
            seen += capfd.readouterr().err
            if len(re.findall(r"hello-from-worker-\d", seen)) >= 3:
                break
            time.sleep(0.3)
        assert len(re.findall(r"hello-from-worker-\d", seen)) >= 3, seen
        assert "(worker-" in seen  # source prefix

        # metrics flow: worker flush (2s period) -> GCS -> /metrics
        from ray_tpu.api import _global_worker

        gcs_addr = _global_worker().backend.core.gcs_address
        dash = start_dashboard(gcs_addr, port=0)
        deadline = time.monotonic() + 20
        text = ""
        while time.monotonic() < deadline:
            with urllib.request.urlopen(dash.url + "/metrics", timeout=5) as r:
                text = r.read().decode()
            if "t_worker_tasks 3.0" in text:
                break
            time.sleep(0.5)
        assert "# TYPE t_worker_tasks counter" in text
        assert "t_worker_tasks 3.0" in text, text
        # core raylet metrics ride the same plane
        assert "raylet_workers" in text
        assert "object_store_used_bytes" in text
        dash.stop()
    finally:
        ray_tpu.shutdown()
