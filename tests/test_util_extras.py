"""util extras: ActorPool, Queue, multiprocessing.Pool.

Parity: python/ray/util/actor_pool.py, util/queue.py,
util/multiprocessing/pool.py.
"""

import pytest


@pytest.fixture
def cluster():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def test_actor_pool_ordered_and_unordered(cluster):
    ray = cluster
    from ray_tpu.util.actor_pool import ActorPool

    @ray.remote
    class Doubler:
        def work(self, x):
            return 2 * x

    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.work.remote(v), range(6)))
    assert out == [0, 2, 4, 6, 8, 10]

    out = sorted(pool.map_unordered(lambda a, v: a.work.remote(v), range(6)))
    assert out == [0, 2, 4, 6, 8, 10]

    # submit with every actor busy queues, then drains
    for v in range(4):
        pool.submit(lambda a, v: a.work.remote(v), v)
    got = [pool.get_next() for _ in range(4)]
    assert got == [0, 2, 4, 6]


def test_queue_cross_task(cluster):
    ray = cluster
    from ray_tpu.util.queue import Empty, Full, Queue

    q = Queue(maxsize=3)
    q.put(1)
    q.put_batch([2, 3])
    with pytest.raises(Full):
        q.put(4, block=False)
    assert q.qsize() == 3

    @ray.remote
    def consume(queue):
        return [queue.get(timeout=10) for _ in range(3)]

    assert ray.get(consume.remote(q), timeout=60) == [1, 2, 3]
    with pytest.raises(Empty):
        q.get_nowait()


def test_multiprocessing_pool(cluster):
    from ray_tpu.util.multiprocessing import Pool

    def sq(x):
        return x * x

    with Pool(processes=2) as p:
        assert p.map(sq, range(5)) == [0, 1, 4, 9, 16]
        assert p.apply(sq, (7,)) == 49
        assert list(p.imap(sq, range(4))) == [0, 1, 4, 9]
        assert sorted(p.imap_unordered(sq, range(4))) == [0, 1, 4, 9]
        r = p.map_async(sq, [3, 4])
        assert r.get(timeout=60) == [9, 16]


def test_pubsub_cross_process(ray_start_regular):
    """General pubsub (util/pubsub.py over the GCS push path): a driver
    subscriber receives messages published from REMOTE worker processes,
    in order, with no polling; unsubscribed channels stay silent."""
    import ray_tpu
    from ray_tpu.util.pubsub import Subscriber, publish

    sub = Subscriber(["alerts", "metrics"])

    @ray_tpu.remote
    def announce(i):
        from ray_tpu.util.pubsub import publish as pub

        n = pub("alerts", {"i": i})
        pub("other", {"i": i})  # nobody listens to this one
        return n

    counts = ray_tpu.get([announce.remote(i) for i in range(3)], timeout=60)
    assert all(c >= 1 for c in counts)  # the driver subscriber was counted

    got = []
    for _ in range(3):
        msg = sub.get_message(timeout=30)
        assert msg is not None
        got.append(msg)
    assert {ch for ch, _ in got} == {"alerts"}
    assert sorted(m["i"] for _, m in got) == [0, 1, 2]
    assert sub.get_message(timeout=0.5) is None  # "other" never delivered

    publish("metrics", {"v": 7})
    ch, m = sub.get_message(timeout=30)
    assert (ch, m) == ("metrics", {"v": 7})

    sub.close()
    publish("alerts", {"late": True})
    assert sub.get_message(timeout=1.0) is None  # closed: no delivery


def test_pubsub_multiple_subscribers_one_process(ray_start_regular):
    """Two Subscribers on one channel in the same process BOTH receive
    every message; closing one must not break the survivor (per-process
    fan-out over the single shared GCS connection)."""
    from ray_tpu.util.pubsub import Subscriber, publish

    s1 = Subscriber(["fan"])
    s2 = Subscriber(["fan"])
    publish("fan", 1)
    assert s1.get_message(timeout=20) == ("fan", 1)
    assert s2.get_message(timeout=20) == ("fan", 1)

    s1.close()
    publish("fan", 2)
    assert s2.get_message(timeout=20) == ("fan", 2)  # survivor still live
    assert s1.get_message(timeout=0.5) is None
    s2.close()
