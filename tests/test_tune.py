"""Tune layer tests: search spaces, ASHA pruning, PBT exploits, Tuner API.

Parity model: tune/tests/ — scheduler simulations with mock trainables
(SURVEY.md §4.5). The PBT test is the VERDICT round-2 "done" bar: PBT mutates
hyperparams across >= 8 concurrent trials and Tuner(JaxTrainer).fit() runs.
"""

import numpy as np
import pytest

from ray_tpu.tune import (
    ASHAScheduler,
    PopulationBasedTraining,
    Trainable,
    TuneConfig,
    Tuner,
    choice,
    grid_search,
    loguniform,
    uniform,
)
from ray_tpu.tune.search import BasicVariantGenerator
from ray_tpu.tune.trial import ERROR, TERMINATED


class TestSearchSpaces:
    def test_grid_cross_product_and_samples(self):
        gen = BasicVariantGenerator(
            {"a": grid_search([1, 2, 3]), "b": grid_search(["x", "y"]),
             "c": uniform(0, 1), "fixed": 7},
            num_samples=2, seed=0,
        )
        configs = list(gen.configs())
        assert len(configs) == 12  # 3 * 2 grid, x2 samples
        assert {(c["a"], c["b"]) for c in configs} == {
            (a, b) for a in (1, 2, 3) for b in ("x", "y")
        }
        assert all(0 <= c["c"] <= 1 and c["fixed"] == 7 for c in configs)

    def test_loguniform_range(self):
        gen = BasicVariantGenerator({"lr": loguniform(1e-5, 1e-1)},
                                    num_samples=50, seed=1)
        vals = [c["lr"] for c in gen.configs()]
        assert all(1e-5 <= v <= 1e-1 for v in vals)
        # log-spread: both decades below 1e-3 and above should appear
        assert any(v < 1e-3 for v in vals) and any(v > 1e-3 for v in vals)


class _Quadratic(Trainable):
    """score climbs toward -(x-3)^2 asymptotically; good x → good score."""

    def step(self):
        x = self.config["x"]
        target = -((x - 3.0) ** 2)
        score = target * (1 - 0.5 ** self.iteration if self.iteration else 0.0)
        return {"score": target - abs(target) * 0.5 ** (self.iteration + 1)}


class _CheckpointedCounter(Trainable):
    def setup(self, config):
        self.total = 0.0

    def step(self):
        self.total += self.config.get("increment", 1.0)
        return {"score": self.total}

    def save_checkpoint(self, checkpoint_dir):
        return {"total": self.total}

    def load_checkpoint(self, checkpoint):
        self.total = checkpoint["total"]

    def reset_config(self, new_config):
        self.config = dict(new_config)
        return True


class TestTunerLocal:
    def test_grid_search_finds_best(self, ray_start_local):
        tuner = Tuner(
            _Quadratic,
            param_space={"x": grid_search([0.0, 1.0, 3.0, 5.0])},
            tune_config=TuneConfig(metric="score", mode="max", num_samples=1),
            run_config=_stop(training_iteration=3),
        )
        grid = tuner.fit()
        assert len(grid) == 4
        best = grid.get_best_result()
        assert best.config["x"] == 3.0

    def test_function_trainable(self, ray_start_local):
        def objective(config):
            return {"score": -(config["x"] - 2.0) ** 2, "done": True}

        grid = Tuner(
            objective,
            param_space={"x": grid_search([0.0, 2.0])},
            tune_config=TuneConfig(metric="score", mode="max"),
        ).fit()
        assert grid.get_best_result().config["x"] == 2.0

    def test_trial_error_isolated(self, ray_start_local):
        def sometimes_fails(config):
            if config["x"] == 1:
                raise RuntimeError("boom")
            return {"score": config["x"], "done": True}

        grid = Tuner(
            sometimes_fails,
            param_space={"x": grid_search([0, 1, 2])},
            tune_config=TuneConfig(metric="score", mode="max"),
        ).fit()
        assert grid.num_errors == 1
        assert grid.get_best_result().config["x"] == 2


class TestASHA:
    def test_bad_trials_stopped_early(self, ray_start_local):
        scheduler = ASHAScheduler(max_t=16, grace_period=2, reduction_factor=2)
        tuner = Tuner(
            _Quadratic,
            param_space={"x": grid_search([0.0, 0.5, 1.0, 2.5, 3.0, 3.5, 5.0, 6.0])},
            tune_config=TuneConfig(
                metric="score", mode="max", scheduler=scheduler,
                max_concurrent_trials=8,
            ),
            run_config=_stop(training_iteration=16),
        )
        grid = tuner.fit()
        iters = {t.config["x"]: t.iteration for t in grid}
        # the best configs survive to max_t; the worst are cut early
        assert iters[3.0] == 16
        assert iters[6.0] < 16
        assert grid.get_best_result().config["x"] == 3.0


class TestPBT:
    def test_exploit_mutates_and_clones(self, ray_start_regular):
        """>= 8 concurrent trials; bottom trials must adopt top checkpoints
        (score jumps to cloned total) and mutated hyperparams."""
        scheduler = PopulationBasedTraining(
            perturbation_interval=2,
            hyperparam_mutations={"increment": [0.25, 0.5, 1.0, 2.0, 4.0]},
            quantile_fraction=0.25,
            seed=0,
        )
        incs = [0.25, 0.25, 0.5, 0.5, 1.0, 1.0, 2.0, 4.0]
        tuner = Tuner(
            _CheckpointedCounter,
            param_space={"increment": grid_search(incs)},
            tune_config=TuneConfig(
                metric="score", mode="max", scheduler=scheduler,
                max_concurrent_trials=8,
            ),
            run_config=_stop(training_iteration=10),
        )
        grid = tuner.fit()
        assert scheduler.num_perturbations >= 1
        # at least one trial's config was mutated away from its grid value
        mutated = [
            t for t, inc0 in zip(grid.trials, incs)
            if t.config["increment"] != inc0
        ]
        assert mutated, "PBT never exploited"
        # exploited trials cloned a better total: their final score must
        # exceed what their original increment alone could produce
        best = grid.get_best_result()
        assert best.metric("score") >= 4.0 * 2  # top increment for >=2 iters


def _stop(**criteria):
    class _RC:
        stop = dict(criteria)

    return _RC()


class TestTunerOverJaxTrainer:
    def test_tuner_wraps_jax_trainer(self, ray_start_regular):
        """Tuner(JaxTrainer).fit() runs trials that each do a tiny jax train
        loop through the Train layer (VERDICT round-2 'done' bar)."""
        from ray_tpu.train import JaxTrainer, ScalingConfig
        from ray_tpu.train.session import report

        def train_loop(config):
            import jax
            import jax.numpy as jnp

            lr = config["lr"]
            w = jnp.zeros(())
            for step in range(3):
                g = 2 * (w - 1.0)
                w = w - lr * g
                report({"loss": float((w - 1.0) ** 2), "lr": lr})

        trainer = JaxTrainer(
            train_loop,
            train_loop_config={"lr": 0.1},
            scaling_config=ScalingConfig(num_workers=1, use_tpu=False),
        )
        grid = Tuner(
            trainer,
            param_space={"lr": grid_search([0.1, 0.5])},
            tune_config=TuneConfig(metric="loss", mode="min"),
        ).fit()
        assert len(grid) == 2
        assert grid.num_errors == 0
        best = grid.get_best_result()
        assert best.config["lr"] == 0.5


class TestHyperBandAndMedian:
    def test_hyperband_brackets_prune_and_keep_best(self, ray_start_local):
        """Bracketed async halving: the best config survives to max_t, bad
        ones are cut early, and trials actually spread across >1 bracket."""
        from ray_tpu.tune import HyperBandScheduler

        scheduler = HyperBandScheduler(max_t=16, grace_period=2,
                                       reduction_factor=2)
        assert len(scheduler.brackets) > 1  # a real bracket portfolio
        tuner = Tuner(
            _Quadratic,
            param_space={"x": grid_search(
                [0.0, 0.5, 1.0, 2.5, 3.0, 3.5, 5.0, 6.0])},
            tune_config=TuneConfig(
                metric="score", mode="max", scheduler=scheduler,
                max_concurrent_trials=8,
            ),
            run_config=_stop(training_iteration=16),
        )
        grid = tuner.fit()
        iters = {t.config["x"]: t.iteration for t in grid}
        assert iters[3.0] == 16                  # the optimum survives
        assert min(iters.values()) < 16          # something was pruned
        assert len(set(scheduler._trial_bracket.values())) > 1
        assert grid.get_best_result().config["x"] == 3.0

    def test_median_stopping_rule(self, ray_start_local):
        """Trials whose running mean is below the peer median stop early;
        above-median trials run to completion."""
        from ray_tpu.tune import MedianStoppingRule

        scheduler = MedianStoppingRule(grace_period=3, min_samples_required=3)
        tuner = Tuner(
            _Quadratic,
            param_space={"x": grid_search(
                [0.0, 1.0, 2.5, 3.0, 3.5, 5.0, 6.0, 7.0])},
            tune_config=TuneConfig(
                metric="score", mode="max", scheduler=scheduler,
                max_concurrent_trials=8,
            ),
            run_config=_stop(training_iteration=12),
        )
        grid = tuner.fit()
        iters = {t.config["x"]: t.iteration for t in grid}
        assert iters[3.0] == 12                  # near-optimum never stopped
        assert iters[7.0] < 12                   # far-off config cut early
        assert grid.get_best_result().config["x"] == 3.0
