"""Serve layer tests: deploy, route, scale, recover, HTTP.

Parity model: python/ray/serve/tests/ (real cluster, real HTTP).
"""

import json
import time
import urllib.request

import pytest


@pytest.fixture(scope="module")
def serve_cluster():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    from ray_tpu import serve

    yield ray_tpu, serve
    serve.shutdown()
    ray_tpu.shutdown()


def test_function_deployment_roundtrip(serve_cluster):
    ray, serve = serve_cluster

    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo)
    out = ray.get(handle.remote("hi"), timeout=60)
    assert out == {"echo": "hi"}
    serve.delete("echo")


def test_class_deployment_with_state_and_replicas(serve_cluster):
    ray, serve = serve_cluster

    @serve.deployment(num_replicas=2)
    class Doubler:
        def __init__(self, factor):
            self.factor = factor

        def __call__(self, x):
            import os

            return {"value": x * self.factor, "pid": os.getpid()}

    handle = serve.run(Doubler.bind(3))
    outs = ray.get([handle.remote(i) for i in range(20)], timeout=90)
    assert [o["value"] for o in outs] == [i * 3 for i in range(20)]
    # both replicas served traffic (power-of-two-choices spreads load)
    assert len({o["pid"] for o in outs}) == 2
    status = serve.status()
    assert status["Doubler"]["running"] == 2
    serve.delete("Doubler")


def test_replica_death_recovery(serve_cluster):
    ray, serve = serve_cluster

    @serve.deployment(num_replicas=1, name="frail")
    def frail(x):
        return x + 1

    handle = serve.run(frail)
    assert ray.get(handle.remote(1), timeout=60) == 2

    # kill the only replica out from under the controller
    from ray_tpu.serve import api as serve_api

    table = ray.get(
        serve_api._local["controller"].routing_table.remote(-1), timeout=30
    )
    (replica,) = table["deployments"]["frail"]
    ray.kill(replica)

    # the controller's reconcile loop must start a replacement
    deadline = time.time() + 60
    ok = False
    while time.time() < deadline:
        try:
            if ray.get(handle.remote(10), timeout=15) == 11:
                ok = True
                break
        except Exception:
            time.sleep(1)
    assert ok, "deployment did not recover from replica death"
    serve.delete("frail")


def test_http_proxy(serve_cluster):
    ray, serve = serve_cluster

    @serve.deployment(name="adder", route_prefix="/add")
    def adder(payload):
        return {"sum": payload["a"] + payload["b"]}

    serve.run(adder, http=True)
    addr = serve.http_address()
    assert addr

    req = urllib.request.Request(
        addr + "/add",
        data=json.dumps({"a": 2, "b": 40}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        body = json.loads(resp.read())
    assert body["result"]["sum"] == 42

    with urllib.request.urlopen(addr + "/-/healthz", timeout=30) as resp:
        assert json.loads(resp.read())["status"] == "ok"

    # unknown route → 404
    try:
        urllib.request.urlopen(addr + "/nope", timeout=30)
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404
    serve.delete("adder")
