"""Serve layer tests: deploy, route, scale, recover, HTTP.

Parity model: python/ray/serve/tests/ (real cluster, real HTTP).
"""

import json
import time
import urllib.request

import pytest


@pytest.fixture(scope="module")
def serve_cluster():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    from ray_tpu import serve

    yield ray_tpu, serve
    serve.shutdown()
    ray_tpu.shutdown()


def test_function_deployment_roundtrip(serve_cluster):
    ray, serve = serve_cluster

    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo)
    out = ray.get(handle.remote("hi"), timeout=60)
    assert out == {"echo": "hi"}
    serve.delete("echo")


def test_class_deployment_with_state_and_replicas(serve_cluster):
    ray, serve = serve_cluster

    @serve.deployment(num_replicas=2)
    class Doubler:
        def __init__(self, factor):
            self.factor = factor

        def __call__(self, x):
            import os

            return {"value": x * self.factor, "pid": os.getpid()}

    handle = serve.run(Doubler.bind(3))
    outs = ray.get([handle.remote(i) for i in range(20)], timeout=90)
    assert [o["value"] for o in outs] == [i * 3 for i in range(20)]
    # both replicas served traffic (power-of-two-choices spreads load)
    assert len({o["pid"] for o in outs}) == 2
    status = serve.status()
    assert status["Doubler"]["running"] == 2
    serve.delete("Doubler")


def test_replica_death_recovery(serve_cluster):
    ray, serve = serve_cluster

    @serve.deployment(num_replicas=1, name="frail")
    def frail(x):
        return x + 1

    handle = serve.run(frail)
    assert ray.get(handle.remote(1), timeout=60) == 2

    # kill the only replica out from under the controller
    from ray_tpu.serve import api as serve_api

    table = ray.get(
        serve_api._local["controller"].routing_table.remote(-1), timeout=30
    )
    (replica,) = table["deployments"]["frail"]
    ray.kill(replica)

    # the controller's reconcile loop must start a replacement
    deadline = time.time() + 60
    ok = False
    while time.time() < deadline:
        try:
            if ray.get(handle.remote(10), timeout=15) == 11:
                ok = True
                break
        except Exception:
            time.sleep(1)
    assert ok, "deployment did not recover from replica death"
    serve.delete("frail")


def test_http_proxy(serve_cluster):
    ray, serve = serve_cluster

    @serve.deployment(name="adder", route_prefix="/add")
    def adder(payload):
        return {"sum": payload["a"] + payload["b"]}

    serve.run(adder, http=True)
    addr = serve.http_address()
    assert addr

    req = urllib.request.Request(
        addr + "/add",
        data=json.dumps({"a": 2, "b": 40}).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        body = json.loads(resp.read())
    assert body["result"]["sum"] == 42

    with urllib.request.urlopen(addr + "/-/healthz", timeout=30) as resp:
        assert json.loads(resp.read())["status"] == "ok"

    # unknown route → 404
    try:
        urllib.request.urlopen(addr + "/nope", timeout=30)
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404
    serve.delete("adder")


def test_batch_decorator_unit():
    """@serve.batch standalone: batching, order, timeout flush, errors."""
    import concurrent.futures

    from ray_tpu.serve.batching import batch

    sizes = []

    @batch(max_batch_size=4, batch_wait_timeout_s=0.1)
    def double(xs):
        sizes.append(len(xs))
        return [x * 2 for x in xs]

    # concurrent callers coalesce into one batch
    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        out = list(pool.map(double, range(8)))
    assert out == [x * 2 for x in range(8)]
    assert max(sizes) > 1, sizes
    # a single call still flushes after the timeout
    assert double(21) == 42

    class Sad:
        @batch(max_batch_size=2, batch_wait_timeout_s=0.05)
        def boom(self, xs):
            raise RuntimeError("nope")

    s = Sad()
    with pytest.raises(RuntimeError, match="nope"):
        s.boom(1)

    class WrongArity:
        @batch(max_batch_size=2, batch_wait_timeout_s=0.05)
        def bad(self, xs):
            return [1]  # wrong length on 2-item batches

    w = WrongArity()
    with concurrent.futures.ThreadPoolExecutor(2) as pool:
        futs = [pool.submit(w.bad, i) for i in range(2)]
        with pytest.raises(TypeError, match="one result per input"):
            for f in futs:
                f.result()


def test_batched_deployment_over_http(serve_cluster):
    """N concurrent HTTP requests are observed by the replica as >=1 batched
    call (parity: serve/batching.py — the TPU serving primitive)."""
    import concurrent.futures

    ray, serve = serve_cluster

    @serve.deployment(
        name="batcher", route_prefix="/batch", max_ongoing_requests=32
    )
    class Batcher:
        def __init__(self):
            self.sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.25)
        def __call__(self, payloads):
            self.sizes.append(len(payloads))
            return [{"doubled": p["x"] * 2, "batch": len(payloads)}
                    for p in payloads]

    serve.run(Batcher, http=True)
    addr = serve.http_address()

    # wait for the proxy's route table to pick up the new deployment
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            probe = urllib.request.Request(
                addr + "/batch", data=json.dumps({"x": 0}).encode(),
                headers={"Content-Type": "application/json"}, method="POST",
            )
            with urllib.request.urlopen(probe, timeout=30):
                break
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
            time.sleep(0.25)

    def post(i):
        req = urllib.request.Request(
            addr + "/batch",
            data=json.dumps({"x": i}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())["result"]

    with concurrent.futures.ThreadPoolExecutor(8) as pool:
        results = list(pool.map(post, range(8)))

    assert [r["doubled"] for r in results] == [2 * i for i in range(8)]
    # at least one multi-request batch formed on the replica
    assert max(r["batch"] for r in results) > 1, results
    serve.delete("batcher")


def test_http_proxy_under_concurrency(serve_cluster):
    """Proxy load smoke (r3 verdict weak #7): 32 concurrent requests across
    2 replicas all succeed through the stdlib proxy."""
    import concurrent.futures

    ray, serve = serve_cluster

    @serve.deployment(name="echo32", route_prefix="/echo32", num_replicas=2,
                      max_ongoing_requests=16)
    def echo(payload):
        return {"v": payload["v"]}

    serve.run(echo, http=True)
    addr = serve.http_address()

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            probe = urllib.request.Request(
                addr + "/echo32", data=json.dumps({"v": -1}).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            with urllib.request.urlopen(probe, timeout=30):
                break
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
            time.sleep(0.25)

    def post(i):
        req = urllib.request.Request(
            addr + "/echo32", data=json.dumps({"v": i}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())["result"]["v"]

    t0 = time.monotonic()
    with concurrent.futures.ThreadPoolExecutor(16) as pool:
        out = list(pool.map(post, range(32)))
    dt = time.monotonic() - t0
    assert sorted(out) == list(range(32))
    assert dt < 60, f"32 concurrent requests took {dt:.1f}s"
    serve.delete("echo32")


def test_streaming_responses(serve_cluster):
    """Generator deployments stream: chunks flow through handle.stream()
    and over HTTP chunked transfer (parity: _private/replica.py:231)."""
    ray, serve = serve_cluster

    @serve.deployment(name="streamer", route_prefix="/sse")
    def streamer(payload):
        def gen():
            for i in range(int(payload["n"])):
                yield {"i": i, "sq": i * i}
        return gen()

    handle = serve.run(streamer, http=True)

    # handle-side streaming
    out = list(handle.stream({"n": 5}))
    assert out == [{"i": i, "sq": i * i} for i in range(5)]

    # HTTP chunked transfer
    import http.client
    addr = serve.http_address().replace("http://", "")
    host, port = addr.rsplit(":", 1)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        conn = http.client.HTTPConnection(host, int(port), timeout=60)
        conn.request("POST", "/sse", body=json.dumps({"n": 4}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status == 200:
            break
        resp.read()
        conn.close()
        time.sleep(0.25)
    assert resp.status == 200
    assert resp.headers.get("Transfer-Encoding") == "chunked"
    lines = [json.loads(l) for l in resp.read().decode().strip().split("\n")]
    assert lines == [{"i": i, "sq": i * i} for i in range(4)]
    conn.close()
    serve.delete("streamer")


def test_multiplexed_models(serve_cluster):
    """@serve.multiplexed: per-replica LRU of loaded models with eviction +
    unload (parity: serve/multiplex.py)."""
    ray, serve = serve_cluster

    @serve.deployment(name="multi", max_ongoing_requests=8)
    class Multi:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            from ray_tpu.serve import get_multiplexed_model_id

            assert get_multiplexed_model_id() == model_id
            self.loads.append(model_id)
            return {"id": model_id, "scale": int(model_id[1:])}

        def __call__(self, req):
            m = self.get_model(req["model"])
            return {"y": req["x"] * m["scale"], "loads": list(self.loads)}

    handle = serve.run(Multi)
    # m1, m2 load once each; repeated use hits the LRU
    r1 = ray.get(handle.remote({"model": "m2", "x": 10}), timeout=60)
    r2 = ray.get(handle.remote({"model": "m3", "x": 10}), timeout=60)
    r3 = ray.get(handle.remote({"model": "m2", "x": 7}), timeout=60)
    assert (r1["y"], r2["y"], r3["y"]) == (20, 30, 14)
    assert r3["loads"] == ["m2", "m3"]  # cached: no reload of m2
    # a third model evicts the LRU entry (m3 was most recent... m2 touched
    # last → m3 evicted)
    r4 = ray.get(handle.remote({"model": "m5", "x": 1}), timeout=60)
    assert r4["loads"] == ["m2", "m3", "m5"]
    r5 = ray.get(handle.remote({"model": "m3", "x": 1}), timeout=60)
    assert r5["loads"] == ["m2", "m3", "m5", "m3"]  # m3 was evicted → reload
    serve.delete("multi")


def test_abandoned_stream_reaped():
    """A stream a client never drains must not leak in the replica: idle
    entries are reaped on the next stream registration, and the underlying
    generator is closed (ADVICE r4: serve/replica.py abandoned-stream leak)."""
    from ray_tpu.serve import replica as replica_mod
    from ray_tpu.serve.replica import ServeReplica

    closed = []

    def streamer(n):
        try:
            for i in range(n):
                yield i
        finally:
            closed.append(n)

    r = ServeReplica(streamer, (), {})
    out1 = r.handle_request(3)
    sid1 = out1["__serve_stream__"]
    # partially drained, then abandoned
    assert r.next_chunk(sid1) == {"done": False, "value": 0}

    old = replica_mod.STREAM_IDLE_TIMEOUT_S
    replica_mod.STREAM_IDLE_TIMEOUT_S = 0.0
    try:
        import time

        time.sleep(0.01)
        out2 = r.handle_request(5)  # registration triggers the reap
    finally:
        replica_mod.STREAM_IDLE_TIMEOUT_S = old
    assert closed == [3]           # abandoned generator was close()d
    assert sid1 not in r._streams  # and its entry dropped
    # a reaped stream must surface an ERROR on next access, never a silent
    # clean end-of-stream (the response would be truncated)
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="reaped"):
        r.next_chunk(sid1)
    # the fresh stream still works end to end
    sid2 = out2["__serve_stream__"]
    got = []
    while True:
        c = r.next_chunk(sid2)
        if c["done"]:
            break
        got.append(c["value"])
    assert got == list(range(5))
    assert r._streams == {}


def test_deployment_composition_graph(serve_cluster):
    """Application graph via nested bind (parity: serve model composition /
    deployment graphs): serve.run(Ingress.bind(pre=Preprocess.bind(),
    models=[A.bind(), B.bind()])) deploys the dependencies bottom-up and
    the ingress replica receives live handles — a diamond DAG per request."""
    ray, serve = serve_cluster

    @serve.deployment(name="pre")
    class Preprocess:
        def __call__(self, text):
            return text.strip().lower()

    @serve.deployment(name="model_a")
    class ModelA:
        def __call__(self, text):
            return {"a_len": len(text)}

    @serve.deployment(name="model_b")
    class ModelB:
        def __call__(self, text):
            return {"b_words": len(text.split())}

    @serve.deployment(name="ingress")
    class Ingress:
        def __init__(self, pre, models):
            self.pre = pre            # DeploymentHandle, resolved in-replica
            self.models = models      # list of handles

        def __call__(self, text):
            import ray_tpu

            clean = ray_tpu.get(self.pre.remote(text), timeout=30)
            outs = ray_tpu.get(
                [m.remote(clean) for m in self.models], timeout=30
            )
            merged = {}
            for o in outs:
                merged.update(o)
            merged["clean"] = clean
            return merged

    app = Ingress.bind(
        pre=Preprocess.bind(), models=[ModelA.bind(), ModelB.bind()]
    )
    handle = serve.run(app)
    out = ray.get(handle.remote("  Hello Composed WORLD  "), timeout=60)
    assert out == {"a_len": 20, "b_words": 3, "clean": "hello composed world"}

    # dependencies are real deployments: individually addressable
    pre_handle = serve.get_handle("pre")
    assert ray.get(pre_handle.remote("  X "), timeout=30) == "x"
    for name in ("ingress", "model_a", "model_b", "pre"):
        serve.delete(name)


def test_compiled_handle_recompiles_on_replica_death(serve_cluster):
    """ROADMAP cgraph-FT gap: when a compiled handle's pinned replica dies,
    the handle recompiles over a HEALTHY replica and re-dispatches the
    failed request — callers keep their refs; no manual recompile."""
    ray, serve = serve_cluster

    @serve.deployment(name="ft_doubler", num_replicas=2)
    class Doubler:
        def __call__(self, x):
            return 2 * x

    handle = serve.run(Doubler.bind())
    compiled = handle.compile(max_in_flight=4)
    try:
        assert compiled.remote(21).get(timeout=30) == 42
        pinned = compiled._replica
        ray.kill(pinned, no_restart=True)
        time.sleep(0.5)
        # the next dispatch observes the death, recompiles, and retries
        assert compiled.remote(5).get(timeout=60) == 10
        assert (
            compiled._replica._actor_id.binary()
            != pinned._actor_id.binary()
        )
        assert compiled.remote(7).get(timeout=30) == 14
    finally:
        compiled.teardown()
        serve.delete("ft_doubler")
