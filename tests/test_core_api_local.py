"""Core API semantics in local mode: tasks, actors, objects, errors.

Mirrors the reference's basic API tests (python/ray/tests/test_basic.py et al.).
"""

import time

import numpy as np
import pytest


def test_task_roundtrip(ray_start_local):
    ray = ray_start_local

    @ray.remote
    def add(a, b):
        return a + b

    assert ray.get(add.remote(1, 2)) == 3


def test_task_chaining_and_ref_args(ray_start_local):
    ray = ray_start_local

    @ray.remote
    def double(x):
        return 2 * x

    ref = double.remote(double.remote(double.remote(1)))
    assert ray.get(ref) == 8


def test_put_get_numpy_roundtrip(ray_start_local):
    ray = ray_start_local
    arr = np.arange(100_000, dtype=np.float32).reshape(1000, 100)
    ref = ray.put(arr)
    out = ray.get(ref)
    np.testing.assert_array_equal(arr, out)


def test_num_returns(ray_start_local):
    ray = ray_start_local

    @ray.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert ray.get([a, b, c]) == [1, 2, 3]


def test_options_override(ray_start_local):
    ray = ray_start_local

    @ray.remote
    def pair():
        return "x", "y"

    refs = pair.options(num_returns=2).remote()
    assert ray.get(refs) == ["x", "y"]


def test_task_error_propagates(ray_start_local):
    ray = ray_start_local

    @ray.remote
    def boom():
        raise ValueError("kapow")

    with pytest.raises(ValueError, match="kapow"):
        ray.get(boom.remote())


def test_wait(ray_start_local):
    ray = ray_start_local

    @ray.remote
    def fast():
        return "fast"

    @ray.remote
    def slow():
        time.sleep(5)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, not_ready = ray.wait([f, s], num_returns=1, timeout=3)
    assert ready == [f] and not_ready == [s]


def test_get_timeout(ray_start_local):
    ray = ray_start_local

    @ray.remote
    def hang():
        time.sleep(60)

    with pytest.raises(ray.exceptions.GetTimeoutError):
        ray.get(hang.remote(), timeout=0.2)


def test_actor_basics(ray_start_local):
    ray = ray_start_local

    @ray.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    refs = [c.incr.remote() for _ in range(5)]
    assert ray.get(refs) == [11, 12, 13, 14, 15]  # ordered execution
    assert ray.get(c.value.remote()) == 15


def test_actor_handle_passing(ray_start_local):
    ray = ray_start_local

    @ray.remote
    class Holder:
        def __init__(self):
            self.v = 7

        def value(self):
            return self.v

    @ray.remote
    def reader(h):
        return ray.get(h.value.remote())

    h = Holder.remote()
    assert ray.get(reader.remote(h)) == 7


def test_named_actor(ray_start_local):
    ray = ray_start_local

    @ray.remote
    class Svc:
        def ping(self):
            return "pong"

    creator_handle = Svc.options(name="svc").remote()  # keep alive (non-detached)
    h = ray.get_actor("svc")
    assert ray.get(h.ping.remote()) == "pong"


def test_actor_error(ray_start_local):
    ray = ray_start_local

    @ray.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor oops")

    b = Bad.remote()
    with pytest.raises(RuntimeError, match="actor oops"):
        ray.get(b.fail.remote())


def test_nested_tasks(ray_start_local):
    ray = ray_start_local

    @ray.remote
    def leaf(x):
        return x * x

    @ray.remote
    def parent(n):
        return sum(ray.get([leaf.remote(i) for i in range(n)]))

    assert ray.get(parent.remote(4)) == 0 + 1 + 4 + 9


def test_retry_exceptions(ray_start_local):
    ray = ray_start_local
    state = {"n": 0}

    @ray.remote(retry_exceptions=True, max_retries=3)
    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise OSError("transient")
        return state["n"]

    assert ray.get(flaky.remote()) == 3


def test_serialization_oob_buffers():
    from ray_tpu.core.serialization import dumps, loads

    arr = np.random.rand(512, 512)
    data = dumps({"a": arr, "b": [1, "x"]})
    out = loads(data)
    np.testing.assert_array_equal(out["a"], arr)
    assert out["b"] == [1, "x"]


def test_resource_set_arithmetic():
    from ray_tpu.core.resources import ResourceSet

    total = ResourceSet({"CPU": 4, "TPU": 8})
    demand = ResourceSet({"CPU": 1, "TPU": 2})
    assert total.fits(demand)
    rem = total.subtract(demand)
    assert rem.get("CPU") == 3 and rem.get("TPU") == 6
    assert not ResourceSet({"CPU": 0.5}).fits(ResourceSet({"CPU": 1}))
    # fixed-point: no float drift for fractional cpus
    r = ResourceSet({"CPU": 4})
    for _ in range(40):
        r = r.subtract(ResourceSet({"CPU": 0.1}))
    assert r.get("CPU") == 0.0
