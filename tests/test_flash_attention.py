"""Flash-attention kernel numerics vs the XLA reference path (CPU interpret).

Reference for *behavior* is plain softmax attention; the reference repo has no
flash/SP implementation at all (SURVEY.md §2.10), so these are fresh numerics.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import (
    flash_attention,
    flash_attention_with_lse,
)


def ref_attention(q, k, v, causal=True):
    S, Skv = q.shape[1], k.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, Skv), dtype=bool))
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))


def make_qkv(key, B=2, S=256, H=4, hd=64, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (B, S, H, hd), dtype)
    k = jax.random.normal(k2, (B, S, H, hd), dtype)
    v = jax.random.normal(k3, (B, S, H, hd), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = make_qkv(jax.random.PRNGKey(0))
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    ref = ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_forward_nondivisible_block_fallback():
    # S=160 not divisible by 64 → _pick_block halves until it divides
    q, k, v = make_qkv(jax.random.PRNGKey(1), S=160)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    q, k, v = make_qkv(jax.random.PRNGKey(2), B=1, S=128, H=2, hd=32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(jnp.sin(o.astype(jnp.float32)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(ref_attention(q, k, v, causal=causal)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_lse_and_offsets():
    """Global offsets: computing attention of a q chunk against a kv chunk at
    a rotated position must equal the corresponding slice of full attention."""
    B, S, H, hd = 1, 128, 2, 32
    q, k, v = make_qkv(jax.random.PRNGKey(3), B=B, S=S, H=H, hd=hd)
    half = S // 2

    # full causal attention, second half of queries
    ref = ref_attention(q, k, v, causal=True)[:, half:]

    # ring-style: q2 against kv chunk 0 (fully visible) and kv chunk 1 (causal)
    q2 = q[:, half:]
    o_a, lse_a = flash_attention_with_lse(
        q2, k[:, :half], v[:, :half], half, 0, block_q=32, block_k=32
    )
    o_b, lse_b = flash_attention_with_lse(
        q2, k[:, half:], v[:, half:], half, half, block_q=32, block_k=32
    )
    # merge partials by lse
    m = jnp.maximum(lse_a, lse_b)
    wa = jnp.exp(lse_a - m)[..., None]   # [B,H,Sq,1]
    wb = jnp.exp(lse_b - m)[..., None]
    oa = jnp.moveaxis(o_a.astype(jnp.float32), 1, 2)  # [B,H,S,hd]
    ob = jnp.moveaxis(o_b.astype(jnp.float32), 1, 2)
    merged = (oa * wa + ob * wb) / (wa + wb)
    merged = jnp.moveaxis(merged, 2, 1)
    np.testing.assert_allclose(
        np.asarray(merged), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_fully_masked_chunk_is_zero_weight():
    """A kv chunk entirely in the future must come back with lse ≈ -inf and
    contribute nothing after the merge."""
    B, S, H, hd = 1, 64, 1, 32
    q, k, v = make_qkv(jax.random.PRNGKey(4), B=B, S=S, H=H, hd=hd)
    # kv offset far beyond all queries
    o, lse = flash_attention_with_lse(
        q, k, v, 0, 10_000, block_q=32, block_k=32
    )
    assert np.all(np.asarray(lse) < -1e29)
    np.testing.assert_array_equal(np.asarray(o), 0.0)


@pytest.mark.parametrize("block_h", [2, 4])
def test_block_h_matches_reference(block_h):
    """Multi-head-per-grid-step kernels (block_h>1) must match numerics of
    the reference, fwd and grad."""
    q, k, v = make_qkv(jax.random.PRNGKey(7), H=4)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                            block_h=block_h)
        return jnp.sum(o.astype(jnp.float32) ** 2), o

    (l, out), grads = jax.value_and_grad(loss, argnums=(0, 1, 2),
                                         has_aux=True)(q, k, v)
    ref = ref_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_ref(q, k, v):
        return jnp.sum(ref_attention(q, k, v, causal=True) ** 2)

    ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   atol=5e-4, rtol=5e-4)
