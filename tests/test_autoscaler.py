"""Autoscaler: scale-up on queued demand, scale-down on idle.

Parity: autoscaler/_private/autoscaler.py:172 reconcile loop semantics.
"""

import time

import pytest


@pytest.fixture
def cluster():
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    c = Cluster(head_node_args={"num_cpus": 1})
    ray_tpu.init(address=c.address)
    yield ray_tpu, c
    ray_tpu.shutdown()
    c.shutdown()


def _mk(ray, c, **kw):
    from ray_tpu.api import _global_worker
    from ray_tpu.autoscaler import LocalNodeProvider, StandardAutoscaler

    core = _global_worker().backend.core

    def gcs_call(method, **k):
        async def call():
            return await core.gcs.call(method, timeout=30, **k)

        return core.io.run(call(), timeout=60)

    provider = LocalNodeProvider(c.address, c.session)
    return provider, StandardAutoscaler(provider, gcs_call, **kw)


def test_scales_up_on_queued_demand_and_down_when_idle(cluster):
    ray, c = cluster
    provider, scaler = _mk(
        ray, c, max_workers=2, upscale_delay_s=0.5, idle_timeout_s=3.0,
        node_resources={"CPU": 2}, poll_period_s=0.3,
    )
    scaler.start()
    try:
        # the 1-CPU head can't serve CPU:2 tasks -> they queue -> scale up
        @ray.remote(num_cpus=2)
        def big(x):
            return x + 1

        refs = [big.remote(i) for i in range(3)]
        assert ray.get(refs, timeout=120) == [1, 2, 3]
        assert len(provider.non_terminated_nodes()) >= 1
        assert any("scale-up" in e for e in scaler.events)

        # drain: nothing queued -> idle timeout reclaims the node
        deadline = time.time() + 60
        while provider.non_terminated_nodes() and time.time() < deadline:
            time.sleep(0.5)
        assert provider.non_terminated_nodes() == []
        assert any("scale-down" in e for e in scaler.events)
    finally:
        scaler.stop()
        provider.shutdown()


def test_request_resources_hint_scales_without_load(cluster):
    ray, c = cluster
    provider, scaler = _mk(
        ray, c, max_workers=1, upscale_delay_s=0.3,
        node_resources={"CPU": 4}, poll_period_s=0.3,
        idle_timeout_s=3600,
    )
    scaler.start()
    try:
        scaler.request_resources([{"CPU": 4}])  # no node fits 4 CPUs yet
        deadline = time.time() + 30
        while not provider.non_terminated_nodes() and time.time() < deadline:
            time.sleep(0.3)
        assert len(provider.non_terminated_nodes()) == 1
        c.wait_for_nodes(2, timeout=30)
        # the hint is now satisfiable -> no further scale-up (max_workers=1)
        assert ray.get(
            ray_remote_cpu4(ray).remote(), timeout=60
        ) == "ok"
    finally:
        scaler.stop()
        provider.shutdown()


def ray_remote_cpu4(ray):
    @ray.remote(num_cpus=4)
    def probe():
        return "ok"

    return probe
