"""Autoscaler: scale-up on queued demand, scale-down on idle.

Parity: autoscaler/_private/autoscaler.py:172 reconcile loop semantics.
"""

import time

import pytest


@pytest.fixture
def cluster():
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    c = Cluster(head_node_args={"num_cpus": 1})
    ray_tpu.init(address=c.address)
    yield ray_tpu, c
    ray_tpu.shutdown()
    c.shutdown()


def _mk(ray, c, **kw):
    from ray_tpu.api import _global_worker
    from ray_tpu.autoscaler import LocalNodeProvider, StandardAutoscaler

    core = _global_worker().backend.core

    def gcs_call(method, **k):
        async def call():
            return await core.gcs.call(method, timeout=30, **k)

        return core.io.run(call(), timeout=60)

    provider = LocalNodeProvider(c.address, c.session)
    return provider, StandardAutoscaler(provider, gcs_call, **kw)


def test_scales_up_on_queued_demand_and_down_when_idle(cluster):
    ray, c = cluster
    provider, scaler = _mk(
        ray, c, max_workers=2, upscale_delay_s=0.5, idle_timeout_s=3.0,
        node_resources={"CPU": 2}, poll_period_s=0.3,
    )
    scaler.start()
    try:
        # the 1-CPU head can't serve CPU:2 tasks -> they queue -> scale up
        @ray.remote(num_cpus=2)
        def big(x):
            return x + 1

        refs = [big.remote(i) for i in range(3)]
        assert ray.get(refs, timeout=120) == [1, 2, 3]
        assert len(provider.non_terminated_nodes()) >= 1
        assert any("scale-up" in e for e in scaler.events)

        # drain: nothing queued -> idle timeout reclaims the node
        deadline = time.time() + 60
        while provider.non_terminated_nodes() and time.time() < deadline:
            time.sleep(0.5)
        assert provider.non_terminated_nodes() == []
        assert any("scale-down" in e for e in scaler.events)
    finally:
        scaler.stop()
        provider.shutdown()


def test_request_resources_hint_scales_without_load(cluster):
    ray, c = cluster
    provider, scaler = _mk(
        ray, c, max_workers=1, upscale_delay_s=0.3,
        node_resources={"CPU": 4}, poll_period_s=0.3,
        idle_timeout_s=3600,
    )
    scaler.start()
    try:
        scaler.request_resources([{"CPU": 4}])  # no node fits 4 CPUs yet
        deadline = time.time() + 30
        while not provider.non_terminated_nodes() and time.time() < deadline:
            time.sleep(0.3)
        assert len(provider.non_terminated_nodes()) == 1
        c.wait_for_nodes(2, timeout=30)
        # the hint is now satisfiable -> no further scale-up (max_workers=1)
        assert ray.get(
            ray_remote_cpu4(ray).remote(), timeout=60
        ) == "ok"
    finally:
        scaler.stop()
        provider.shutdown()


def ray_remote_cpu4(ray):
    @ray.remote(num_cpus=4)
    def probe():
        return "ok"

    return probe


def test_tpu_pod_provider_lifecycle():
    """TpuPodProvider drives the queued-resources API surface (parity:
    autoscaler/_private/gcp/ + fake_multi_node test-double spirit): create
    posts a QR with the node spec + bootstrap script, non_terminated_nodes
    tracks WAITING→PROVISIONING→ACTIVE, terminate deletes."""
    from ray_tpu.autoscaler.tpu_pod_provider import (
        FakeTpuApiTransport,
        TpuPodProvider,
    )

    api = FakeTpuApiTransport(provision_ticks=2)
    provider = TpuPodProvider(
        "proj", "us-central2-b",
        accelerator_type="v5litepod-8",
        gcs_address="10.0.0.2:6379",
        transport=api,
    )
    n1 = provider.create_node({"TPU": 8})
    n2 = provider.create_node({"TPU": 8})
    # the QR carried the right node spec + cluster-join bootstrap
    method, path, body = api.calls[0]
    assert method == "POST" and "queuedResources" in path
    node = body["tpu"]["node_spec"][0]["node"]
    assert node["accelerator_type"] == "v5litepod-8"
    assert "10.0.0.2:6379" in node["metadata"]["startup-script"]

    # visible while provisioning; state advances per poll
    assert set(provider.non_terminated_nodes()) == {n1, n2}
    provider.non_terminated_nodes()
    assert provider.node_state(n1) == "ACTIVE"

    provider.terminate_node(n1)
    assert provider.non_terminated_nodes() == [n2]
    provider.shutdown()
    assert provider.non_terminated_nodes() == []


def test_autoscaler_drives_tpu_pod_provider():
    """StandardAutoscaler scale-up/down decisions flow through the TPU
    provider's API surface (no real cluster needed: canned GCS load)."""
    from ray_tpu.autoscaler import StandardAutoscaler
    from ray_tpu.autoscaler.tpu_pod_provider import (
        FakeTpuApiTransport,
        TpuPodProvider,
    )

    api = FakeTpuApiTransport(provision_ticks=1)
    provider = TpuPodProvider(
        "proj", "us-central2-b", gcs_address="gcs:1", transport=api
    )
    load = {"nodes": {}, "pending_actors": 0}
    sa = StandardAutoscaler(
        provider,
        gcs_call=lambda method, **kw: load,
        min_workers=0, max_workers=2,
        upscale_delay_s=0.0, idle_timeout_s=0.05,
        node_resources={"TPU": 8},
    )
    # queued TPU demand → scale up one slice per reconcile window
    load["nodes"] = {
        "head": {"alive": True, "pending": [{"TPU": 8}],
                 "available": {}, "total": {"CPU": 1}},
    }
    sa.reconcile()
    sa.reconcile()
    slices = provider.non_terminated_nodes()
    assert len(slices) >= 1
    assert any("queuedResources" in p for _, p, _ in api.calls)

    # demand gone + slice idle → terminate through the provider
    sid = slices[0]
    load["nodes"] = {
        "head": {"alive": True, "pending": [],
                 "available": {"CPU": 1}, "total": {"CPU": 1}},
        sid: {"alive": True, "pending": [],
              "available": {"TPU": 8}, "total": {"TPU": 8}},
    }
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and provider.non_terminated_nodes():
        sa.reconcile()
        time.sleep(0.05)
    assert sid not in provider.non_terminated_nodes()
