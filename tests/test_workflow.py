"""Workflow layer: durable DAG execution, checkpointing, resume.

Parity: python/ray/workflow/ (api.py run/resume, workflow_storage.py).
"""

import os

import pytest


@pytest.fixture
def wf(tmp_path):
    import ray_tpu
    from ray_tpu import workflow

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    workflow.init(str(tmp_path / "wf_store"))
    yield ray_tpu, workflow
    ray_tpu.shutdown()


def test_workflow_runs_dag_and_checkpoints(wf, tmp_path):
    ray, workflow = wf

    @ray.remote
    def double(x):
        return 2 * x

    @ray.remote
    def add(a, b):
        return a + b

    dag = add.bind(double.bind(3), double.bind(4))
    out = workflow.run(dag, workflow_id="w1")
    assert out == 14
    assert workflow.get_status("w1") == "SUCCESSFUL"
    assert workflow.get_output("w1") == 14
    assert ("w1", "SUCCESSFUL") in workflow.list_all()


def test_workflow_resume_skips_completed_steps(wf, tmp_path):
    ray, workflow = wf
    marker_dir = tmp_path / "markers"
    marker_dir.mkdir()

    @ray.remote
    def record(tag):
        # side-effect counter: one file per EXECUTION
        n = len(os.listdir(marker_dir))
        (marker_dir / f"{tag}-{n}").write_text("x")
        return tag

    @ray.remote
    def fail_once(a, b):
        flag = marker_dir / "fail-armed"
        if flag.exists():
            flag.unlink()
            raise RuntimeError("injected step failure")
        return f"{a}+{b}"

    (marker_dir / "fail-armed").write_text("x")
    dag = fail_once.bind(record.bind("left"), record.bind("right"))

    with pytest.raises(Exception, match="injected"):
        workflow.run(dag, workflow_id="w2")
    assert workflow.get_status("w2") == "FAILED"
    executed = len(list(marker_dir.iterdir()))  # left + right ran

    out = workflow.resume("w2")
    assert out == "left+right"
    assert workflow.get_status("w2") == "SUCCESSFUL"
    # the two record() steps were checkpointed: resume must NOT re-run them
    assert len(list(marker_dir.iterdir())) == executed


def test_workflow_resume_of_finished_returns_output(wf):
    ray, workflow = wf

    @ray.remote
    def one():
        return 1

    workflow.run(one.bind(), workflow_id="w3")
    assert workflow.resume("w3") == 1


def test_workflow_input_value(wf):
    ray, workflow = wf
    from ray_tpu.dag import InputNode

    @ray.remote
    def inc(x):
        return x + 1

    with InputNode() as inp:
        dag = inc.bind(inp)
    assert workflow.run(dag, workflow_id="w4", input_value=41) == 42
