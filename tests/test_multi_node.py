"""Multi-raylet (multi-"node") scheduling, object transfer, and chaos tests.

Parity: python/ray/cluster_utils.py Cluster fixture + test_chaos.py patterns
(SIGKILL a raylet under load, assert recovery/errors surface cleanly).
"""

import time

import numpy as np
import pytest


@pytest.fixture(scope="module")
def two_node_cluster():
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"num_cpus": 1, "resources": {"head": 1}})
    cluster.add_node(num_cpus=1, resources={"side": 1})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(2)
    yield ray_tpu, cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_two_nodes_visible(two_node_cluster):
    ray, cluster = two_node_cluster
    nodes = [n for n in ray.nodes() if n["Alive"]]
    assert len(nodes) == 2
    assert ray.cluster_resources().get("CPU") == 2.0


def test_spillback_schedules_on_remote_node(two_node_cluster):
    """Demand that only fits the second node must spill over to it."""
    ray, cluster = two_node_cluster

    @ray.remote(resources={"side": 1})
    def where():
        import os

        return os.environ.get("RAY_TPU_NODE_ID")

    node_id = ray.get(where.remote(), timeout=90)
    assert node_id == cluster.node_ids[1]


def test_parallelism_across_nodes(two_node_cluster):
    """Two 1-CPU nodes must run two 1-CPU tasks concurrently."""
    ray, cluster = two_node_cluster

    @ray.remote(resources={"head": 0.01})
    def warm_head():
        return 1

    @ray.remote(resources={"side": 0.01})
    def warm_side():
        return 1

    # warm both nodes' worker pools so the timing below measures scheduling,
    # not interpreter cold start on this 1-core host
    ray.get([warm_head.remote(), warm_side.remote()], timeout=120)

    @ray.remote
    def block(sec):
        time.sleep(sec)
        return time.time()

    t0 = time.time()
    ray.get([block.remote(3), block.remote(3)], timeout=120)
    elapsed = time.time() - t0
    assert elapsed < 5.5, f"tasks serialized: {elapsed}s"


def test_object_transfer_between_nodes(two_node_cluster):
    """A large object produced on node B is readable from the driver (node A)
    via raylet pull (push/pull transfer path)."""
    ray, cluster = two_node_cluster

    @ray.remote(resources={"side": 1})
    def produce():
        return np.full((256, 256), 7.0)

    @ray.remote(resources={"head": 1})
    def consume(x):
        return float(x.sum())

    ref = produce.remote()
    out = ray.get(ref, timeout=120)  # driver pulls from remote node
    assert out.shape == (256, 256)
    # cross-node task arg: produced on side, consumed on head
    total = ray.get(consume.remote(produce.remote()), timeout=120)
    assert total == 7.0 * 256 * 256


def test_node_death_detected_and_task_fails(two_node_cluster):
    """SIGKILL the side raylet mid-task: GCS must mark the node dead and the
    pinned task must surface an error rather than hang. Runs LAST (destroys
    the side node)."""
    ray, cluster = two_node_cluster

    @ray.remote(resources={"side": 1}, max_retries=0)
    def hang():
        time.sleep(300)

    ref = hang.remote()
    time.sleep(3)  # let it get scheduled
    cluster.kill_node(cluster.node_ids[1])
    with pytest.raises(ray.exceptions.RayTpuError):
        ray.get(ref, timeout=90)
    # GCS health check marks the node dead
    deadline = time.time() + 60
    while time.time() < deadline:
        alive = [n for n in ray.nodes() if n["Alive"]]
        if len(alive) == 1:
            break
        time.sleep(1)
    assert len([n for n in ray.nodes() if n["Alive"]]) == 1


def test_workers_exit_when_raylet_killed():
    """SIGKILL'd raylets must not orphan their worker processes: each worker
    watches its raylet connection + parent pid and exits (worker_main
    watchdog). Regression: round-3 leak (285 orphans accumulated)."""
    import os

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    def node_worker_pids(node_id: str):
        pids = []
        for pid in os.listdir("/proc"):
            if not pid.isdigit():
                continue
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmd = f.read()
                if b"worker_main" not in cmd:
                    continue
                with open(f"/proc/{pid}/environ", "rb") as f:
                    env = f.read()
                if f"RAY_TPU_NODE_ID={node_id}".encode() in env:
                    pids.append(int(pid))
            except (OSError, PermissionError):
                continue
        return pids

    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"num_cpus": 1})
    victim = cluster.add_node(num_cpus=1, resources={"side": 1})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(2)

        @ray_tpu.remote(resources={"side": 1})
        def touch():
            return os.getpid()

        ray_tpu.get(touch.remote(), timeout=60)
        assert node_worker_pids(victim), "victim node should have live workers"

        cluster.kill_node(victim)
        deadline = time.time() + 15
        while node_worker_pids(victim) and time.time() < deadline:
            time.sleep(0.5)
        assert node_worker_pids(victim) == [], "workers must exit with raylet"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_owner_death_kills_mid_task_worker(tmp_path):
    """When a driver dies, a worker still EXECUTING its task must be killed,
    not recycled to IDLE: the raylet cannot observe the direct owner->worker
    push, so recycling would hand a busy worker to the next owner (ADVICE
    r4: node_manager.on_disconnection). The freed resources must also let a
    new driver's task run."""
    import os
    import signal
    import subprocess
    import sys

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"num_cpus": 1})
    ray_tpu.init(address=cluster.address)
    pidfile = str(tmp_path / "worker_pid")
    script = tmp_path / "driver.py"
    script.write_text(
        "import sys\n"
        "import ray_tpu\n"
        "ray_tpu.init(address=sys.argv[1])\n"
        "@ray_tpu.remote(num_cpus=1)\n"
        "def long_task(pidfile):\n"
        "    import os, time\n"
        "    with open(pidfile + '.tmp', 'w') as f:\n"
        "        f.write(str(os.getpid()))\n"
        "    os.rename(pidfile + '.tmp', pidfile)\n"
        "    time.sleep(300)\n"
        "ray_tpu.get(long_task.remote(sys.argv[2]), timeout=600)\n"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    env = {**os.environ,
           "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")}
    driver = subprocess.Popen(
        [sys.executable, str(script), cluster.address, pidfile],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
    )
    try:
        deadline = time.time() + 90
        while not os.path.exists(pidfile) and time.time() < deadline:
            time.sleep(0.2)
        assert os.path.exists(pidfile), "sub-driver's task never started"
        wpid = int(open(pidfile).read())
        assert os.path.exists(f"/proc/{wpid}")

        driver.send_signal(signal.SIGKILL)
        driver.wait(timeout=10)

        deadline = time.time() + 20
        while os.path.exists(f"/proc/{wpid}") and time.time() < deadline:
            time.sleep(0.2)
        assert not os.path.exists(f"/proc/{wpid}"), (
            "mid-task worker of a dead owner must be killed"
        )

        # the lease's CPU was released: a fresh task can run
        @ray_tpu.remote(num_cpus=1)
        def ping():
            return "ok"

        assert ray_tpu.get(ping.remote(), timeout=60) == "ok"
    finally:
        driver.kill()
        ray_tpu.shutdown()
        cluster.shutdown()
