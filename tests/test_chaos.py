"""Deterministic chaos-injection tests (ray_tpu/testing/chaos.py).

The acceptance triangle of the robustness PR, all driven by seeded plans:
  1. compiled graphs: a mid-pipeline participant death either fails fast
     (ActorDiedError well before the ring timeout, max_restarts=0) or
     recovers (dag.recover() / auto_recover=True, max_restarts=-1);
  2. serve: a replica dying mid-request costs exactly one retry on a
     healthy replica, never a user-visible error;
  3. core FT regression: task retry + lineage reconstruction + actor
     restart under seeded worker kills, replacing ad-hoc sleep-and-kill.

Every test is tier-1 (fast, deterministic) and chaos-marked, so conftest's
SIGALRM guard fails a re-introduced hang quickly instead of stalling the
suite.
"""

import os
import time

import pytest


# --------------------------------------------------------------------------
# plan mechanics (no runtime needed)
# --------------------------------------------------------------------------
def test_plan_roundtrip_env_and_event_log():
    from ray_tpu.testing import chaos

    p = chaos.plan(7).kill_worker(after_tasks=3).sever_rpc("kv_put", nth=2)
    clone = chaos.ChaosPlan.from_json(p.to_json())
    assert clone.seed == 7 and clone.rules == p.rules

    with p:
        assert os.environ[chaos.ENV_PLAN] == p.to_json()
        # deterministic counters: 3rd lease fires, then the rule is spent
        assert chaos.fire("worker.lease") is None
        assert chaos.fire("worker.lease") is None
        act = chaos.fire("worker.lease")
        assert act is not None and act["action"] == "kill"
        assert chaos.fire("worker.lease") is None
        # match filters by substring; nth counts matching events only
        assert chaos.fire("rpc.send", key="kv_get") is None
        assert chaos.fire("rpc.send", key="kv_put") is None
        assert chaos.fire("rpc.send", key="kv_put")["action"] == "sever"
    assert chaos.ENV_PLAN not in os.environ

    events = p.events()
    assert [e["point"] for e in events] == ["worker.lease", "rpc.send"]
    assert all(e["seed"] == 7 for e in events)
    assert [e["action"] for e in events] == ["kill", "sever"]


def test_overlapping_rules_are_not_starved():
    """Two rules matching the same event: one fires, the other must fire on
    the NEXT matching event instead of being counted past its trigger."""
    from ray_tpu.testing import chaos

    p = (chaos.plan(0)
         .kill_actor(match="A", after_calls=1)
         .kill_actor(match="A.b", after_calls=1))
    with p:
        assert chaos.fire("actor.call", key="A.b") is not None  # rule 0 wins
        assert chaos.fire("actor.call", key="A.b") is not None  # rule 1 fires
        assert chaos.fire("actor.call", key="A.b") is None      # both spent
    assert len(p.events()) == 2


def test_rpc_sever_injection_deterministic():
    """The rpc.send hook: the Nth matching frame severs the connection."""
    import pytest as _pytest

    from ray_tpu.core import rpc
    from ray_tpu.testing import chaos

    class Handler:
        def handle_echo(self, conn, x):
            return x * 2

    io = rpc.EventLoopThread(name="chaos-rpc-test")
    try:
        server = rpc.RpcServer(Handler())
        io.run(server.start())
        with chaos.plan(1).sever_rpc("echo", nth=2) as p:
            conn = io.run(rpc.connect(server.address, name="chaos-test"))
            assert io.run(conn.call("echo", x=3, timeout=10)) == 6
            with _pytest.raises(rpc.RpcError):
                io.run(conn.call("echo", x=4, timeout=10))
            assert [e["action"] for e in p.events()] == ["sever"]
        io.run(server.close())
    finally:
        io.stop()


@pytest.mark.chaos(timeout=60)
def test_rpc_sever_mid_batch_fails_unflushed_outbox():
    """PR-6 coalesced wire: a connection severed while a BATCH group is
    still staged (un-flushed) must fail EVERY request in the group with the
    typed, retryable ConnectionLost — no hang, no partial delivery — and a
    fresh connection to the same server must work (retryable)."""
    import asyncio

    from ray_tpu.core import rpc
    from ray_tpu.testing import chaos

    class Handler:
        def __init__(self):
            self.seen = []

        def handle_echo(self, conn, x):
            self.seen.append(x)
            return x

    async def run():
        handler = Handler()
        server = rpc.RpcServer(handler)
        await server.start()
        try:
            with chaos.plan(3).sever_rpc("echo", nth=4) as p:
                conn = await rpc.connect(server.address, name="mid-batch")
                # stage 3 batched requests in ONE loop tick: they sit in the
                # un-flushed stage/outbox when the 4th send severs the wire
                futs = [
                    await conn.call_start_batched("echo", x=i)
                    for i in range(3)
                ]
                with pytest.raises(rpc.ConnectionLost):
                    await conn.call_start_batched("echo", x=99)
                for fut in futs:
                    with pytest.raises(rpc.ConnectionLost):
                        await asyncio.wait_for(fut, 10)
                assert [e["action"] for e in p.events()] == ["sever"]
            # nothing from the severed batch may have reached the handler
            assert handler.seen == []
            # the failure is retryable: a fresh connection works end-to-end
            conn2 = await rpc.connect(server.address, name="retry")
            assert await conn2.call("echo", x=7, timeout=10) == 7
            await conn2.close()
        finally:
            await server.close()

    asyncio.run(run())


@pytest.mark.chaos(timeout=90)
def test_rpc_drop_mid_batch_replay_same_batch_boundaries():
    """Replaying the same seeded plan over the same send schedule must
    reproduce the same injection log AND the same batch boundaries (frames
    sent, frames coalesced, arrival order) — chaos runs are auditable only
    if batching is deterministic under them."""
    import asyncio

    from ray_tpu.core import rpc
    from ray_tpu.testing import chaos

    class Handler:
        def __init__(self):
            self.order = []

        def handle_mark(self, conn, tag):
            self.order.append(tag)

        def handle_sync(self, conn):
            return True

    async def one_run():
        handler = Handler()
        server = rpc.RpcServer(handler)
        await server.start()
        try:
            with chaos.plan(11).drop_rpc("mark", nth=3) as p:
                conn = await rpc.connect(server.address, name="replay")
                base = dict(conn.stats)
                # fixed schedule: groups staged in one tick, fenced by a
                # direct call so each group's flush boundary is deterministic
                for group in (["a0", "a1", "a2", "a3"], ["b0"],
                              ["c0", "c1", "c2"]):
                    for tag in group:
                        await conn.notify_batched("mark", tag=tag)
                    assert await conn.call("sync", timeout=10)
                delta = {
                    k: conn.stats[k] - base[k]
                    for k in ("rpc_frames_sent", "rpc_frames_coalesced")
                }
                events = [
                    (e["point"], e["key"], e["action"], e["count"])
                    for e in p.events()
                ]
                await conn.close()
                return handler.order, delta, events
        finally:
            await server.close()

    first = asyncio.run(one_run())
    second = asyncio.run(one_run())
    assert first == second, "replayed seed must reproduce batch boundaries"
    order, delta, events = first
    # the 3rd mark ("a2") was dropped pre-stage; everything else arrived in
    # enqueue order
    assert order == ["a0", "a1", "a3", "b0", "c0", "c1", "c2"]
    assert events == [("rpc.send", "mark", "drop", 3)]
    assert delta["rpc_frames_coalesced"] >= 3  # groups a and c coalesced


# --------------------------------------------------------------------------
# compiled-graph fault tolerance (local mode, tier-1)
# --------------------------------------------------------------------------
def _make_stages(ray_tpu, **actor_opts):
    dec = ray_tpu.remote(**actor_opts) if actor_opts else ray_tpu.remote

    @dec
    class Stage:
        def __init__(self, k):
            self.k = k

        def head(self, x):
            return x + self.k

        def mid(self, x):
            return x + self.k

        def tail(self, x):
            return x + self.k

    return Stage.remote(1), Stage.remote(10), Stage.remote(100)


def _compile_chain(ray_tpu, a, b, c, **kw):
    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        dag = c.tail.bind(b.mid.bind(a.head.bind(inp)))
    return dag.experimental_compile(max_in_flight=4, **kw)


@pytest.mark.chaos(timeout=90)
def test_cgraph_dead_participant_fails_fast(ray_start_local):
    """max_restarts=0: a killed mid-pipeline actor surfaces as
    ActorDiedError from ref.get() well before the caller's timeout."""
    import ray_tpu
    from ray_tpu.testing import chaos

    a, b, c = _make_stages(ray_tpu)
    compiled = _compile_chain(ray_tpu, a, b, c)
    try:
        assert compiled.execute(0).get(timeout=10) == 111
        time.sleep(0.2)  # let the loops settle on their blocking reads
        with chaos.plan(3).kill_cgraph_actor(match="mid", after_iters=1) as p:
            r1 = compiled.execute(1, timeout=10)
            r2 = compiled.execute(2, timeout=10)
            # seq 1 completes (the kill lands on b's NEXT iteration)...
            assert r1.get(timeout=30) == 112
            # ...seq 2 is lost mid-pipeline: prompt typed error, not a
            # 60s ring-timeout burn
            t0 = time.monotonic()
            with pytest.raises(ray_tpu.exceptions.ActorDiedError):
                r2.get(timeout=60)
            assert time.monotonic() - t0 < 15
            assert [e["action"] for e in p.events()] == ["kill"]
        # the dead participant also fails new submissions fast
        with pytest.raises(ray_tpu.exceptions.ActorDiedError):
            compiled.execute(3, timeout=10)
    finally:
        compiled.teardown()


@pytest.mark.chaos(timeout=90)
def test_cgraph_recover_manual(ray_start_local):
    """max_restarts=-1 + dag.recover(): in-flight seq fails with a precise
    per-seq error; the recovered graph resumes at the next seq."""
    import ray_tpu
    from ray_tpu.testing import chaos

    a, b, c = _make_stages(ray_tpu, max_restarts=-1)
    compiled = _compile_chain(ray_tpu, a, b, c)
    try:
        assert compiled.execute(0).get(timeout=10) == 111
        time.sleep(0.2)
        with chaos.plan(5).kill_cgraph_actor(match="mid", after_iters=1) as p:
            r1 = compiled.execute(1, timeout=10)
            r2 = compiled.execute(2, timeout=10)
            assert r1.get(timeout=30) == 112        # completed before the kill
            with pytest.raises(ray_tpu.exceptions.ActorUnavailableError):
                r2.get(timeout=30)                  # restarting: resumable
            compiled.recover()
            with pytest.raises(ray_tpu.exceptions.ActorDiedError,
                               match="seq=2"):
                r2.get(timeout=10)                  # precise per-seq error
            # the recovered graph computes correctly at the next seqs
            assert compiled.execute(3, timeout=10).get(timeout=30) == 114
            assert compiled.execute(4, timeout=10).get(timeout=30) == 115
            assert [e["action"] for e in p.events()] == ["kill"]
    finally:
        compiled.teardown()


@pytest.mark.chaos(timeout=90)
def test_cgraph_auto_recover(ray_start_local):
    """auto_recover=True: no manual recover() call — the in-flight seq
    resolves with its per-seq error and execution continues."""
    import ray_tpu
    from ray_tpu.testing import chaos

    a, b, c = _make_stages(ray_tpu, max_restarts=-1)
    compiled = _compile_chain(ray_tpu, a, b, c, auto_recover=True)
    try:
        assert compiled.execute(0).get(timeout=10) == 111
        time.sleep(0.2)
        with chaos.plan(6).kill_cgraph_actor(match="mid", after_iters=1) as p:
            r1 = compiled.execute(1, timeout=10)
            r2 = compiled.execute(2, timeout=10)
            assert r1.get(timeout=30) == 112
            with pytest.raises(ray_tpu.exceptions.ActorDiedError,
                               match="seq=2"):
                r2.get(timeout=30)
            assert compiled.execute(3, timeout=10).get(timeout=30) == 114
            assert len(p.events()) == 1
    finally:
        compiled.teardown()


def test_cgraph_result_cache_evicts_abandoned_refs(ray_start_local):
    """ROADMAP-known leak: results for refs never get()'d must not
    accumulate in the driver-side cache once the ref is GC'd."""
    import gc

    import ray_tpu

    a, b, c = _make_stages(ray_tpu)
    compiled = _compile_chain(ray_tpu, a, b, c)
    try:
        # abandon refs without ever get()ing them
        for i in range(8):
            compiled.execute(i, timeout=10)
        gc.collect()
        # a kept ref drains the output rings; abandoned seqs are evicted
        keeper = compiled.execute(99, timeout=10)
        assert keeper.get(timeout=30) == 210
        assert len(compiled._results) == 0, compiled._results
        # the abandoned-seq bookkeeping is consumed, not retained
        assert compiled._abandoned == set()
    finally:
        compiled.teardown()


# --------------------------------------------------------------------------
# serve routing failover (local mode, tier-1)
# --------------------------------------------------------------------------
_SERVE_CALLS = []


@pytest.mark.chaos(timeout=120)
def test_serve_replica_failover_single_retry(ray_start_local):
    """2 replicas; the one serving the request is chaos-killed mid-dispatch:
    the request succeeds after exactly one retry on the healthy replica."""
    import ray_tpu
    from ray_tpu.serve import api as serve
    from ray_tpu.testing import chaos

    _SERVE_CALLS.clear()

    @serve.deployment(name="frail-chaos", num_replicas=2)
    class Frail:
        def __call__(self, x):
            _SERVE_CALLS.append(x)
            return 2 * x

    handle = serve.run(Frail.bind())
    try:
        # warm the routing table outside the plan
        assert ray_tpu.get(handle.remote(1), timeout=60) == 2
        with chaos.plan(11).kill_actor(
            match="ServeReplica.handle_request", after_calls=1
        ) as p:
            assert ray_tpu.get(handle.remote(21), timeout=60) == 42
            assert handle._router.retry_count == 1
            kills = [e for e in p.events() if e["point"] == "actor.call"]
            assert len(kills) == 1
        # the chaos kill fired before user code: the request executed
        # exactly once (on the healthy replica) — no double execution
        assert _SERVE_CALLS.count(21) == 1
        # the dead replica was evicted from the router's local set
        assert len(handle._router._replicas["frail-chaos"]) == 1
    finally:
        serve.shutdown()


# --------------------------------------------------------------------------
# train: worker death → FailureConfig retry from the latest checkpoint
# --------------------------------------------------------------------------
_TRAIN_STARTS = []


def _flaky_train_loop(config):
    from ray_tpu import train

    ckpt = train.get_checkpoint()
    start = int(ckpt.to_dict()["step"]) if ckpt is not None else 0
    _TRAIN_STARTS.append(start)
    for i in range(start, config["total_steps"]):
        train.report(
            {"step": i + 1},
            checkpoint=train.Checkpoint.from_dict({"step": i + 1}),
        )
        time.sleep(0.25)


@pytest.mark.chaos(timeout=150)
def test_trainer_restarts_from_checkpoint_on_worker_death(ray_start_local):
    import ray_tpu  # noqa: F401
    from ray_tpu.testing import chaos
    from ray_tpu.train import DataParallelTrainer, ScalingConfig
    from ray_tpu.train.config import FailureConfig, RunConfig

    _TRAIN_STARTS.clear()
    with chaos.plan(2).kill_actor(match="TrainWorker.poll",
                                  after_calls=2) as p:
        trainer = DataParallelTrainer(
            _flaky_train_loop,
            train_loop_config={"total_steps": 6},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                failure_config=FailureConfig(max_failures=1)
            ),
        )
        result = trainer.fit()
    assert result.error is None, result.error
    # one injected death, one elastic restart FROM THE CHECKPOINT (not 0)
    assert [e["point"] for e in p.events()] == ["actor.call"]
    assert len(_TRAIN_STARTS) == 2, _TRAIN_STARTS
    assert _TRAIN_STARTS[0] == 0 and _TRAIN_STARTS[1] > 0, _TRAIN_STARTS
    assert result.metrics["step"] == 6


# --------------------------------------------------------------------------
# core FT regression under seeded kills (local actor restart + cluster)
# --------------------------------------------------------------------------
@pytest.mark.chaos(timeout=60)
def test_actor_restart_under_seeded_kill(ray_start_local):
    import ray_tpu
    from ray_tpu.testing import chaos

    @ray_tpu.remote(max_restarts=1)
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    with chaos.plan(9).kill_actor(match="Counter.inc", after_calls=3) as p:
        assert ray_tpu.get(c.inc.remote(), timeout=10) == 1
        assert ray_tpu.get(c.inc.remote(), timeout=10) == 2
        with pytest.raises(ray_tpu.exceptions.ActorDiedError):
            ray_tpu.get(c.inc.remote(), timeout=10)  # the seeded kill
        # restarted with FRESH state (cluster restart semantics)
        assert ray_tpu.get(c.inc.remote(), timeout=30) == 1
        assert len(p.events()) == 1


@pytest.mark.chaos(timeout=180)
def test_task_retry_under_seeded_worker_lease_kill():
    """Cluster: the worker granted the 1st lease is SIGKILLed by the plan;
    the task retries transparently and every result is correct."""
    import ray_tpu
    from ray_tpu.testing import chaos

    ray_tpu.shutdown()
    with chaos.plan(6).kill_worker(after_tasks=1) as p:
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:

            @ray_tpu.remote
            def f(x):
                return x + 1

            results = ray_tpu.get(
                [f.remote(i) for i in range(6)], timeout=120
            )
            assert results == [i + 1 for i in range(6)]
            kills = [e for e in p.events() if e["point"] == "worker.lease"]
            assert len(kills) == 1
        finally:
            ray_tpu.shutdown()


@pytest.mark.chaos(timeout=180)
def test_lineage_reconstruction_under_seeded_worker_kill():
    """Cluster: the producing task's first worker is chaos-killed (task
    retry), then the stored copy is lost — the owner lineage-reconstructs."""
    import numpy as np

    import ray_tpu
    from ray_tpu.testing import chaos

    ray_tpu.shutdown()
    with chaos.plan(12).kill_worker(after_tasks=1) as p:
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:

            @ray_tpu.remote(max_retries=3)
            def produce():
                return np.full(1_000_000, 7.0)  # large → lives in shm

            ref = produce.remote()
            assert ray_tpu.get(ref, timeout=120)[0] == 7.0
            assert any(e["point"] == "worker.lease" for e in p.events())

            # now lose the only stored copy out from under the owner
            from ray_tpu.api import _global_worker
            from ray_tpu.core.object_store import shm_store

            core = _global_worker().backend.core
            path = os.path.join(
                shm_store.session_dir(core.session), ref.id.hex()
            )
            assert os.path.exists(path)
            os.unlink(path)

            got = ray_tpu.get(ref, timeout=120)
            assert got[0] == 7.0 and got.shape == (1_000_000,)
        finally:
            ray_tpu.shutdown()


# --------------------------------------------------------------------------
# cluster-mode compiled-graph recovery (real SIGKILL; excluded from tier-1)
# --------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.chaos(timeout=300)
def test_cgraph_recover_cluster_mode():
    """End to end over real worker processes: a participant's worker is
    SIGKILLed mid-pipeline, the GCS restarts the actor, and dag.recover()
    resumes on fresh shm rings."""
    import ray_tpu
    from ray_tpu.testing import chaos

    # the whole cluster must start INSIDE the plan: actor workers inherit
    # their environment (and thus the plan) from the raylet, not the driver
    ray_tpu.shutdown()
    with chaos.plan(13).kill_cgraph_actor(match="mid", after_iters=3):
        ray_tpu.init(num_cpus=4, num_tpus=0)
        a, b, c = _make_stages(ray_tpu, max_restarts=-1)
        compiled = _compile_chain(ray_tpu, a, b, c)
        try:
            # iters 1-2 complete; iter 3 dies mid-pipeline
            assert compiled.execute(0).get(timeout=60) == 111
            r1 = compiled.execute(1, timeout=30)
            try:
                r2 = compiled.execute(2, timeout=30)
            except ray_tpu.exceptions.ActorUnavailableError:
                r2 = None  # the death event beat the submission — fine
            assert r1.get(timeout=60) == 112
            if r2 is not None:
                with pytest.raises(
                    (ray_tpu.exceptions.ActorUnavailableError,
                     ray_tpu.exceptions.ActorDiedError)
                ):
                    r2.get(timeout=60)
            compiled.recover(timeout=120)
            assert compiled.execute(3, timeout=30).get(timeout=60) == 114
        finally:
            compiled.teardown()
            ray_tpu.shutdown()
