"""JaxTrainer end-to-end: BASELINE config 1 (MLP, 1 worker, CPU).

The train loop runs inside a cluster worker process, reports metrics via
session.report, ships an orbax checkpoint, and resumes from it.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def ray_for_train():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def mlp_train_loop(config):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu import train

    rng = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(rng, 3)
    # synthetic MNIST-shaped problem: 784 -> 128 -> 10
    params = {
        "w1": jax.random.normal(k1, (784, 128)) * 0.05,
        "b1": jnp.zeros((128,)),
        "w2": jax.random.normal(k2, (128, 10)) * 0.05,
        "b2": jnp.zeros((10,)),
    }
    ckpt = train.get_checkpoint()
    start_step = 0
    if ckpt is not None:
        restored = ckpt.to_jax(target=jax.device_get(params))
        params = restored["params"] if "params" in restored else restored
        start_step = int(restored.get("step", 0)) if isinstance(restored, dict) else 0

    x = jax.random.normal(k3, (256, 784))
    y = (jnp.arange(256) % 10).astype(jnp.int32)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    def loss_fn(p, x, y):
        h = jax.nn.relu(x @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def step(p, s, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        updates, s = opt.update(g, s)
        return optax.apply_updates(p, updates), s, loss

    num_steps = config.get("num_steps", 10)
    for i in range(start_step, start_step + num_steps):
        params, opt_state, loss = step(params, opt_state, x, y)
        if (i + 1) % 5 == 0 or i == start_step + num_steps - 1:
            ck = train.Checkpoint.from_jax({"params": params, "step": i + 1})
            train.report({"loss": float(loss), "step": i + 1}, checkpoint=ck)
        else:
            train.report({"loss": float(loss), "step": i + 1})


def test_jax_trainer_mlp_learns(ray_for_train):
    from ray_tpu.train import JaxTrainer, ScalingConfig

    trainer = JaxTrainer(
        mlp_train_loop,
        train_loop_config={"num_steps": 12},
        scaling_config=ScalingConfig(num_workers=1),
    )
    result = trainer.fit()
    assert result.error is None
    losses = [m["loss"] for m in result.metrics_dataframe]
    assert len(losses) == 12
    assert losses[-1] < losses[0] * 0.7, losses
    assert result.checkpoint is not None


def test_jax_trainer_resume_from_checkpoint(ray_for_train):
    from ray_tpu.train import JaxTrainer, ScalingConfig

    t1 = JaxTrainer(
        mlp_train_loop,
        train_loop_config={"num_steps": 5},
        scaling_config=ScalingConfig(num_workers=1),
    )
    r1 = t1.fit()
    assert r1.error is None and r1.checkpoint is not None

    t2 = JaxTrainer(
        mlp_train_loop,
        train_loop_config={"num_steps": 5},
        scaling_config=ScalingConfig(num_workers=1),
        resume_from_checkpoint=r1.checkpoint,
    )
    r2 = t2.fit()
    assert r2.error is None
    # resumed run continues from step 5
    assert r2.metrics["step"] == 10


def test_trainer_failure_surfaces(ray_for_train):
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def bad_loop(config):
        raise RuntimeError("train exploded")

    t = JaxTrainer(
        bad_loop, scaling_config=ScalingConfig(num_workers=1)
    )
    result = t.fit()
    assert result.error is not None
    assert "train exploded" in str(result.error)


def test_batch_predictor(ray_start_regular):
    """BatchPredictor runs a JaxPredictor over a Dataset on an actor pool
    (parity: train/batch_predictor.py): model loaded once per worker,
    predictions stream back as a Dataset, pass-through columns preserved."""
    import numpy as np

    import ray_tpu.data as rd
    from ray_tpu.train import BatchPredictor, Checkpoint, JaxPredictor

    # a "trained" linear model: y = x @ w + b
    w = np.asarray([[2.0], [1.0]], np.float32)
    b = np.asarray([0.5], np.float32)
    ckpt = Checkpoint.from_dict({"params": {"w": w, "b": b}})

    def apply_fn(params, batch):
        import jax.numpy as jnp

        x = jnp.stack([jnp.asarray(batch["x0"]), jnp.asarray(batch["x1"])],
                      axis=-1)
        return {"y": (x @ params["w"] + params["b"])[:, 0]}

    rows = [{"x0": float(i), "x1": float(2 * i), "id": i} for i in range(64)]
    ds = rd.from_items(rows, parallelism=4)

    predictor = BatchPredictor.from_checkpoint(
        ckpt, JaxPredictor, apply_fn=apply_fn
    )
    out = predictor.predict(ds, num_workers=2, keep_columns=("id",))
    got = {int(r["id"]): float(r["y"]) for r in out.take_all()}
    assert len(got) == 64
    for i in range(64):
        assert abs(got[i] - (2.0 * i + 1.0 * 2 * i + 0.5)) < 1e-4
