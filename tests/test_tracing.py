"""Tracing + timeline subsystem (ray_tpu/tracing/).

Parity model: src/ray/core_worker/task_event_buffer.h (bounded per-process
buffering, drop counting), gcs_task_manager.h (bounded aggregation, state
API), `ray timeline` (Chrome-trace export), and task-event-based debugging
of the serve/streaming/cgraph hot paths.
"""

import json
import time

import pytest

REQUIRED_TRACE_KEYS = {"pid", "tid", "ts", "ph", "name"}


# ---------------------------------------------------------------- unit level
def test_buffer_bounded_and_drop_counting():
    from ray_tpu.tracing import TaskEventBuffer

    buf = TaskEventBuffer(capacity=100)
    for i in range(150):
        buf.record(task_id=f"{i:032x}", name="t", state="SUBMITTED")
    assert len(buf) == 100
    assert buf.dropped == 50
    events, dropped = buf.drain()
    assert len(events) == 100 and dropped == 50
    assert len(buf) == 0
    # timestamps are strictly monotonic within the process
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts) and len(set(ts)) == len(ts)


def test_sampling_is_deterministic_per_trace():
    from ray_tpu.core.config import _config
    from ray_tpu.tracing import TaskEventBuffer

    buf = TaskEventBuffer(capacity=10_000)
    saved = _config.task_events_sample_rate
    _config.task_events_sample_rate = 0.5
    try:
        # all events of one trace keep or drop together, across repeats
        for trace in ("a" * 32, "b" * 32, "c" * 32, "d" * 32):
            first = buf.record(task_id="1" * 32, trace_id=trace,
                               name="x", state="SUBMITTED")
            for _ in range(5):
                assert buf.record(
                    task_id="2" * 32, trace_id=trace, name="x",
                    state="RUNNING",
                ) == first
    finally:
        _config.task_events_sample_rate = saved


def test_chrome_trace_builder_shapes():
    from ray_tpu.tracing import build_chrome_trace

    t0 = time.time()
    events = [
        {"task_id": "t1", "name": "f", "state": "SUBMITTED", "ts": t0,
         "attempt": 0, "node_id": "n1", "worker": "w1"},
        {"task_id": "t1", "name": "f", "state": "RUNNING", "ts": t0 + 0.01,
         "attempt": 0, "node_id": "n1", "worker": "w2"},
        {"task_id": "t1", "name": "f", "state": "EXECUTED", "ts": t0 + 0.05,
         "attempt": 0, "node_id": "n1", "worker": "w2"},
        {"task_id": "t1", "name": "f", "state": "FINISHED", "ts": t0 + 0.06,
         "attempt": 0, "node_id": "n1", "worker": "w1"},
        {"task_id": None, "name": "span", "state": "PROFILE",
         "ts": t0 + 0.02, "dur": 0.005, "worker": "w2", "node_id": "n1"},
    ]
    trace = build_chrome_trace(events)
    assert all(REQUIRED_TRACE_KEYS <= set(e) for e in trace)
    spans = [e for e in trace if e["ph"] == "X" and e["name"] == "f"]
    assert len(spans) == 1 and abs(spans[0]["dur"] - 40_000) < 1
    assert any(e["ph"] == "X" and e["name"] == "span" for e in trace)
    # valid JSON end to end
    assert json.loads(json.dumps(trace)) == trace


def test_aggregator_event_cap_never_drops_terminal_states():
    """A span-heavy task must not overflow its record into a phantom
    RUNNING: the per-task cap truncates PROFILE spans only."""
    from ray_tpu.tracing import TaskEventAggregator

    agg = TaskEventAggregator(max_tasks=10, max_events_per_task=5)
    events = [{"task_id": "t", "name": "f", "state": "SUBMITTED", "ts": 1.0}]
    events += [
        {"task_id": "t", "name": "s", "state": "PROFILE",
         "ts": 1.0 + i * 1e-3}
        for i in range(20)
    ]
    events += [
        {"task_id": "t", "name": "f", "state": "RUNNING", "ts": 2.0},
        {"task_id": "t", "name": "f", "state": "FINISHED", "ts": 3.0},
    ]
    agg.ingest(events)
    t = agg.get_task("t")
    assert t["state"] == "FINISHED"
    assert sum(1 for e in t["events"] if e["state"] == "PROFILE") == 5
    assert agg.truncated_events == 15


# --------------------------------------------------------------- local mode
def test_local_task_lifecycle_and_state_api(ray_start_local):
    ray = ray_start_local
    from ray_tpu.util import state

    @ray.remote
    def add(x):
        with ray.profile_span("inner-work", args={"x": x}):
            pass
        return x + 1

    refs = [add.remote(i) for i in range(3)]
    assert ray.get(refs) == [1, 2, 3]

    t = state.get_task(refs[0].task_id.hex())
    assert t is not None and t["state"] == "FINISHED"
    states = [e["state"] for e in t["events"]]
    assert states[0] == "SUBMITTED" and "RUNNING" in states
    assert states[-1] == "FINISHED"
    # the profile span landed inside the task's timeline
    assert any(
        e["state"] == "PROFILE" and e["name"] == "inner-work"
        for e in t["events"]
    )

    summary = state.summarize_tasks()
    assert summary["tasks"]["add"]["FINISHED"] == 3
    assert summary["dropped_at_source"] == 0

    rows = state.list_tasks()
    mine = [r for r in rows if r["name"] == "add"]
    assert len(mine) == 3
    assert all(isinstance(r["task_id"], str) for r in mine)  # hex, not bytes

    trace = ray.timeline()
    assert all(REQUIRED_TRACE_KEYS <= set(e) for e in trace)
    assert sum(1 for e in trace if e["name"] == "add" and e["ph"] == "X") >= 3


def test_local_nested_tasks_share_parent_and_trace(ray_start_local):
    ray = ray_start_local
    from ray_tpu.util import state

    @ray.remote
    def child():
        return 1

    @ray.remote
    def parent():
        return ray.get(child.remote())

    ref = parent.remote()
    assert ray.get(ref) == 1
    rows = state.list_tasks()
    child_row = next(r for r in rows if r["name"] == "child")
    t = state.get_task(child_row["task_id"])
    assert any(e.get("parent_id") == ref.task_id.hex() for e in t["events"])


def test_tracing_disabled_records_nothing(ray_start_local):
    ray = ray_start_local
    from ray_tpu.core.config import _config
    from ray_tpu.util import state

    saved = _config.task_events_enabled
    _config.task_events_enabled = False
    try:
        @ray.remote
        def ghost():
            return 0

        ref = ghost.remote()
        assert ray.get(ref) == 0
        assert state.get_task(ref.task_id.hex()) is None
    finally:
        _config.task_events_enabled = saved


@pytest.mark.chaos
def test_chaos_killed_actor_timeline_ends_failed_local(ray_start_local):
    """After an injected worker kill the task's timeline must end FAILED —
    no hang, no phantom RUNNING tail — and the drop counter must be
    accurate (nothing was dropped, so exactly 0)."""
    ray = ray_start_local
    from ray_tpu.testing import chaos
    from ray_tpu.util import state

    with chaos.plan(seed=11).kill_actor(match="Victim.work", after_calls=2):
        @ray.remote(max_restarts=0)
        class Victim:
            def work(self):
                return 1

        v = Victim.remote()
        assert ray.get(v.work.remote(), timeout=30) == 1
        dead_ref = v.work.remote()
        with pytest.raises(ray.exceptions.ActorDiedError):
            ray.get(dead_ref, timeout=30)

    t = state.get_task(dead_ref.task_id.hex())
    assert t is not None and t["state"] == "FAILED"
    lifecycle = [e["state"] for e in t["events"] if e["state"] != "PROFILE"]
    assert lifecycle[-1] == "FAILED", lifecycle
    assert t["dropped_at_source"] == 0
    summary = state.summarize_tasks()
    assert summary["tasks"]["work"].get("FAILED", 0) >= 1


# -------------------------------------------------------------- cluster mode
@pytest.fixture
def cluster():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def _flush_wait():
    # owner/worker/raylet buffers flush on independent ~1s loops
    time.sleep(2.5)


def test_cluster_full_lifecycle_events(cluster):
    ray = cluster
    from ray_tpu.util import state

    @ray.remote
    def work():
        return 1

    ref = work.remote()
    assert ray.get(ref, timeout=60) == 1
    _flush_wait()
    t = state.get_task(ref.task_id.hex())
    states = {e["state"] for e in t["events"]}
    # owner (SUBMITTED/DISPATCHED/FINISHED) + raylet (LEASED) + executing
    # worker (RUNNING/EXECUTED) all contributed to one timeline
    assert {"SUBMITTED", "DISPATCHED", "RUNNING", "FINISHED"} <= states
    assert t["state"] == "FINISHED"
    workers = {e["worker"] for e in t["events"] if e.get("worker")}
    assert len(workers) >= 2  # driver + executing worker


def test_serve_request_stitches_one_trace_across_processes(cluster):
    """Acceptance: a cluster-mode serve request produces a single stitched
    trace spanning >= 3 processes (handle/driver, ingress replica worker,
    nested replica worker), exported as valid Chrome-trace JSON."""
    ray = cluster
    from ray_tpu import serve
    from ray_tpu.util import state

    @serve.deployment
    class Model:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Ingress:
        def __init__(self, model):
            self.model = model

        def __call__(self, x):
            import ray_tpu

            return ray_tpu.get(self.model.remote(x), timeout=30) + 1

    try:
        handle = serve.run(Ingress.bind(Model.bind()))
        assert ray.get(handle.remote(5), timeout=90) == 11
        _flush_wait()

        events = state.timeline_events()
        serve_spans = [
            e for e in events
            if e["state"] == "PROFILE" and e["name"] == "serve.request"
            and e.get("trace_id")
        ]
        assert serve_spans, "serve dispatch recorded no request span"
        # the ingress dispatch span's trace must cover >= 3 processes
        by_trace = {}
        for e in events:
            if e.get("trace_id"):
                by_trace.setdefault(e["trace_id"], []).append(e)
        best = max(
            (evs for evs in by_trace.values()),
            key=lambda evs: len({e.get("worker") for e in evs
                                 if e.get("worker")}),
        )
        workers = {e.get("worker") for e in best if e.get("worker")}
        assert len(workers) >= 3, (
            f"trace spans only {len(workers)} processes: {workers}"
        )
        # the trace contains both replicas' task executions
        names = {e["name"] for e in best}
        assert "handle_request" in names

        # Chrome-trace export: valid JSON, every event fully addressed
        import tempfile

        out = tempfile.mktemp(suffix=".json")
        trace = ray.timeline(out)
        loaded = json.loads(open(out).read())
        assert loaded and loaded == trace
        assert all(REQUIRED_TRACE_KEYS <= set(e) for e in loaded)
    finally:
        serve.shutdown()


def test_serve_stream_backpressure_window_option(cluster):
    """Satellite: the hardcoded window 16 is now a per-deployment option,
    routing-table propagated, overridable per handle."""
    ray = cluster
    from ray_tpu import serve

    @serve.deployment(stream_backpressure_window=3)
    class Chunker:
        def __call__(self, n):
            def gen():
                for i in range(n):
                    yield i
            return gen()

    try:
        handle = serve.run(Chunker.bind())
        router = handle._router
        assert router.backpressure_for("Chunker") == 3
        assert list(handle.stream(5)) == list(range(5))
        # handle-level override plumbs through options()
        h2 = handle.options(stream_backpressure_window=7)
        assert h2._stream_backpressure_window == 7
        assert list(h2.stream(4)) == list(range(4))
        # default when the deployment doesn't set one
        from ray_tpu.serve.handle import DEFAULT_STREAM_BACKPRESSURE

        assert router.backpressure_for("nonexistent") == \
            DEFAULT_STREAM_BACKPRESSURE
    finally:
        serve.shutdown()


@pytest.mark.chaos(timeout=180)
def test_chaos_killed_worker_timeline_ends_failed_cluster():
    """Cluster variant of the chaos acceptance: a real SIGKILL of the actor
    worker mid-call. The dead worker's buffered events die with it (never
    counted as drops by a live source), the owner's FAILED event lands, and
    the aggregate drop counter stays accurate."""
    import ray_tpu
    from ray_tpu.testing import chaos
    from ray_tpu.util import state

    ray_tpu.shutdown()
    with chaos.plan(seed=23).kill_actor(match="Victim.work", after_calls=2):
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            @ray_tpu.remote(max_restarts=0)
            class Victim:
                def work(self):
                    return 1

            v = Victim.remote()
            assert ray_tpu.get(v.work.remote(), timeout=60) == 1
            dead_ref = v.work.remote()
            with pytest.raises(ray_tpu.exceptions.ActorDiedError):
                ray_tpu.get(dead_ref, timeout=60)
            _flush_wait()
            t = state.get_task(dead_ref.task_id.hex())
            assert t is not None and t["state"] == "FAILED"
            lifecycle = [
                e["state"] for e in t["events"] if e["state"] != "PROFILE"
            ]
            assert lifecycle[-1] == "FAILED", lifecycle
            assert isinstance(t["dropped_at_source"], int)
            assert t["dropped_at_source"] == 0
        finally:
            ray_tpu.shutdown()
