"""Data layer tests: blocks, datasources, streaming execution, iteration,
Train integration.

Parity model: python/ray/data/tests/ (operator tests with in-memory blocks,
streaming executor tests — SURVEY.md §4.5).
"""

import numpy as np
import pytest

builtins_range = range  # rd.range shadows the builtin in this module's style

from ray_tpu import data as rd
from ray_tpu.data.block import (
    block_concat,
    block_from_rows,
    block_num_rows,
    block_slice,
)
from ray_tpu.data.executor import ActorPoolStrategy


class TestBlocks:
    def test_rows_roundtrip(self):
        b = block_from_rows([{"a": 1, "b": 2.0}, {"a": 3, "b": 4.0}])
        assert block_num_rows(b) == 2
        assert b["a"].tolist() == [1, 3]
        b2 = block_from_rows([10, 20, 30])
        assert b2["item"].tolist() == [10, 20, 30]

    def test_concat_slice(self):
        b1 = {"x": np.arange(3)}
        b2 = {"x": np.arange(3, 7)}
        cat = block_concat([b1, b2])
        assert block_num_rows(cat) == 7
        assert block_slice(cat, 2, 5)["x"].tolist() == [2, 3, 4]


class TestDatasetLocal:
    def test_range_count_take(self, ray_start_local):
        ds = rd.range(100, parallelism=4)
        assert ds.count() == 100
        assert ds.take(5) == [{"id": 0}, {"id": 1}, {"id": 2}, {"id": 3}, {"id": 4}]

    def test_map_batches_streaming(self, ray_start_local):
        ds = rd.range(64, parallelism=4).map_batches(
            lambda b: {"id": b["id"], "sq": b["id"] ** 2}
        )
        rows = ds.take_all()
        assert len(rows) == 64
        assert all(r["sq"] == r["id"] ** 2 for r in rows)

    def test_chained_map_and_filter(self, ray_start_local):
        ds = (
            rd.range(50, parallelism=4)
            .map_batches(lambda b: {"id": b["id"] * 2})
            .filter(lambda r: r["id"] % 4 == 0)
        )
        assert sorted(r["id"] for r in ds.take_all()) == list(range(0, 100, 4))

    def test_map_batches_with_batch_size(self, ray_start_local):
        def stamp_size(b):
            n = block_num_rows(b)
            return {"id": b["id"], "bs": np.full(n, n)}

        ds = rd.range(100, parallelism=3).map_batches(stamp_size, batch_size=32)
        rows = ds.take_all()
        assert len(rows) == 100
        # rechunked: 32/32/32/4 — every row stamped with its batch's size
        from collections import Counter

        counts = Counter(r["bs"] for r in rows)
        assert counts == {32: 96, 4: 4}

    def test_actor_pool_callable_class(self, ray_start_regular):
        class AddConst:
            def __init__(self, c):
                self.c = c

            def __call__(self, block):
                return {"id": block["id"] + self.c}

        ds = rd.range(40, parallelism=4).map_batches(
            AddConst, fn_args=(1000,), compute=ActorPoolStrategy(size=2)
        )
        rows = sorted(r["id"] for r in ds.take_all())
        assert rows == list(range(1000, 1040))

    def test_limit(self, ray_start_local):
        assert rd.range(1000, parallelism=8).limit(17).count() == 17

    def test_from_items_and_numpy(self, ray_start_local):
        ds = rd.from_items([{"v": i} for i in range(10)])
        assert ds.count() == 10
        ds2 = rd.from_numpy(np.ones((5, 3)))
        assert ds2.count() == 5
        assert ds2.take(1)[0]["data"].shape == (3,)

    def test_split_balanced(self, ray_start_local):
        shards = rd.range(103, parallelism=5).split(4)
        counts = [s.count() for s in shards]
        assert sum(counts) == 103
        assert max(counts) - min(counts) <= 3
        # shards are disjoint and cover the range
        ids = sorted(r["id"] for s in shards for r in s.take_all())
        assert ids == list(range(103))

    def test_iter_batches_exact_sizes(self, ray_start_local):
        batches = list(
            rd.range(70, parallelism=3).iter_batches(batch_size=32)
        )
        assert [len(b["id"]) for b in batches] == [32, 32, 6]
        batches = list(
            rd.range(70, parallelism=3).iter_batches(batch_size=32, drop_last=True)
        )
        assert [len(b["id"]) for b in batches] == [32, 32]

    def test_iter_batches_to_device(self, ray_start_local):
        import jax

        dev = jax.devices("cpu")[0]
        batches = list(
            rd.range(16, parallelism=2).iter_batches(batch_size=8, device=dev)
        )
        assert len(batches) == 2
        assert isinstance(batches[0]["id"], jax.Array)
        assert batches[0]["id"].sum() == sum(range(8))

    def test_iter_stacked_batches(self, ray_start_local):
        """multi_step_fn delivery: batches stacked on a leading step axis,
        one device_put per stack; trailing partial stacks drop."""
        import jax

        from ray_tpu.data.iterator import iter_stacked_batches

        ds = rd.range(70, parallelism=3)
        stacks = list(iter_stacked_batches(
            ds.iter_block_refs(), batch_size=16, steps_per_stack=2
        ))
        # 70 rows -> 4 full batches of 16 -> 2 stacks of [2, 16]
        assert [s["id"].shape for s in stacks] == [(2, 16), (2, 16)]
        assert stacks[0]["id"][0].tolist() == list(range(16))

        sh = jax.sharding.SingleDeviceSharding(jax.devices("cpu")[0])
        stacks = list(iter_stacked_batches(
            rd.range(64, parallelism=2).iter_block_refs(),
            batch_size=8, steps_per_stack=4, stacked_sharding=sh,
        ))
        assert len(stacks) == 2
        assert isinstance(stacks[0]["id"], jax.Array)
        assert stacks[0]["id"].shape == (4, 8)


class TestFileIO:
    def test_parquet_roundtrip(self, ray_start_local, tmp_path):
        pa = pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        for i in range(3):
            t = pa.table({"x": list(range(i * 10, i * 10 + 10)),
                          "y": [float(v) for v in range(10)]})
            pq.write_table(t, str(tmp_path / f"part-{i}.parquet"))
        ds = rd.read_parquet(str(tmp_path))
        assert ds.count() == 30
        assert ds.schema()["x"] == "int64"
        assert sorted(r["x"] for r in ds.take_all()) == list(range(30))

    def test_csv(self, ray_start_local, tmp_path):
        pytest.importorskip("pyarrow")
        p = tmp_path / "data.csv"
        p.write_text("a,b\n1,x\n2,y\n3,z\n")
        ds = rd.read_csv(str(p))
        assert ds.count() == 3
        assert ds.take(1)[0]["a"] == 1


class TestTrainIntegration:
    def test_trainer_feeds_from_dataset(self, ray_start_regular):
        """JaxTrainer ingests a Dataset via get_dataset_shard → iter_batches
        (VERDICT round-2 item 4: train from a Dataset, not synthetic_batch)."""
        from ray_tpu.train import JaxTrainer, ScalingConfig, get_dataset_shard, report

        ds = rd.range(64, parallelism=4).map_batches(
            lambda b: {"x": b["id"].astype(np.float32),
                       "y": (b["id"] * 3 + 1).astype(np.float32)}
        )

        def train_loop(config):
            import jax
            import jax.numpy as jnp

            shard = get_dataset_shard("train")
            w = jnp.zeros(2)  # fit y = a*x + b
            seen = 0
            for _ in range(3):  # epochs
                for batch in shard.iter_batches(batch_size=8):
                    x, y = jnp.asarray(batch["x"]), jnp.asarray(batch["y"])
                    seen += int(x.shape[0])

                    def loss(w):
                        return jnp.mean((w[0] * x + w[1] - y) ** 2)

                    w = w - 0.01 * jax.grad(loss)(w)
            report({"rows_seen": seen, "final_loss": float(
                jnp.mean((w[0] * jnp.asarray(batch["x"]) + w[1]
                          - jnp.asarray(batch["y"])) ** 2))})

        trainer = JaxTrainer(
            train_loop,
            scaling_config=ScalingConfig(num_workers=2, use_tpu=False),
            datasets={"train": ds},
        )
        result = trainer.fit()
        assert result.error is None
        # each of 2 workers saw its 32-row shard 3 times
        assert result.metrics["rows_seen"] == 96
        all_ranks = result.metrics["_all_ranks"]
        assert set(all_ranks) == {0, 1}
        assert all(m["rows_seen"] == 96 for m in all_ranks.values())


def test_flat_map_union_repartition(ray_start_local):
    rdata = rd
    ds = rdata.from_items([1, 2, 3]).flat_map(lambda r: [int(r)] * int(r))
    assert sorted(int(r) for r in ds.take_all()) == [1, 2, 2, 3, 3, 3]

    a = rdata.from_items([1, 2])
    b = rdata.from_items([3, 4])
    assert sorted(int(r) for r in a.union(b).take_all()) == [1, 2, 3, 4]

    rp = rdata.range(10, parallelism=5).repartition(2)
    refs = list(rp.iter_block_refs())
    assert len(refs) == 2
    assert sorted(r["id"] for r in rp.take_all()) == list(range(10))


def test_sort_and_groupby(ray_start_local):
    rdata = rd
    items = [{"k": i % 3, "v": float(i)} for i in range(12)]
    ds = rdata.from_items(items)

    s = ds.sort("v", descending=True).take_all()
    assert [r["v"] for r in s] == sorted((float(i) for i in range(12)),
                                         reverse=True)

    g = ds.groupby("k")
    assert g.count() == {0: 4, 1: 4, 2: 4}
    assert g.sum("v") == {0: 0 + 3 + 6 + 9, 1: 1 + 4 + 7 + 10, 2: 2 + 5 + 8 + 11}
    assert g.mean("v")[0] == (0 + 3 + 6 + 9) / 4
    assert g.min("v") == {0: 0.0, 1: 1.0, 2: 2.0}
    assert g.max("v") == {0: 9.0, 1: 10.0, 2: 11.0}


def test_transforms_chain_after_materialized_ops(ray_start_local):
    # regression: map after union/sort must not silently drop the data
    a = rd.from_items([3, 1])
    b = rd.from_items([2, 4])
    u = a.union(b).map(lambda r: int(r) * 10)
    assert sorted(int(r) for r in u.take_all()) == [10, 20, 30, 40]

    s = rd.from_items([{"k": "b"}, {"k": "a"}]).sort("k")
    assert [r["k"] for r in s.take_all()] == ["a", "b"]
    assert s.limit(1).take_all()[0]["k"] == "a"


def test_distributed_shuffle_sort(ray_start_regular):
    """Range-partitioned shuffle sort (data/shuffle.py ↔ reference
    push_based_shuffle.py): output stays MULTI-block (never concatenated on
    the driver), globally ordered across block boundaries."""
    import numpy as np

    rng = np.random.default_rng(7)
    vals = rng.permutation(500).astype(np.int64)
    ds = rd.from_items([{"v": int(v)} for v in vals], parallelism=8)
    out = ds.sort("v", num_partitions=4)
    refs = list(out.iter_block_refs())
    assert len(refs) == 4  # partitioned output, not one driver-side concat
    got = [int(r["v"]) for r in out.take_all()]
    assert got == sorted(range(500))

    # descending too
    got_d = [int(r["v"]) for r in ds.sort("v", descending=True).take_all()]
    assert got_d == sorted(range(500), reverse=True)


def test_distributed_random_shuffle_global(ray_start_regular):
    """random_shuffle is a GLOBAL shuffle: rows cross block boundaries, the
    multiset is preserved, and the seed makes it deterministic."""
    ds = rd.range(200, parallelism=4)
    out = ds.random_shuffle(seed=3)
    rows = [int(r["id"]) for r in out.take_all()]
    assert sorted(rows) == list(range(200))
    assert rows != list(range(200))  # actually shuffled
    # global: the first output partition must contain rows from >1 input
    # block (input blocks are contiguous ranges of 50)
    first_block = __import__("ray_tpu").get(next(iter(out.iter_block_refs())))
    first = [int(v) for v in first_block["id"]]
    assert len({v // 50 for v in first}) > 1, first
    # determinism
    again = [int(r["id"]) for r in ds.random_shuffle(seed=3).take_all()]
    assert rows == again


def test_groupby_map_groups_shuffled(ray_start_regular):
    """map_groups rides the hash shuffle: every key's rows meet in one task."""
    items = [{"k": i % 5, "v": float(i)} for i in range(100)]
    ds = rd.from_items(items, parallelism=8)

    def spread(group):
        vs = np.asarray(group["v"])
        return {"k": group["k"][:1], "spread": np.asarray([vs.max() - vs.min()])}

    out = ds.groupby("k").map_groups(spread, num_partitions=3)
    rows = {int(r["k"]): float(r["spread"]) for r in out.take_all()}
    assert rows == {k: 95.0 for k in range(5)}


def test_groupby_string_keys_cross_process(ray_start_regular):
    """String keys must route to the SAME partition from every map task.

    Map tasks run in separate worker processes whose builtins.hash salts
    differ (PYTHONHASHSEED is unset) — a per-process hash would scatter one
    key across partitions and map_groups would emit duplicated groups.
    The partitioner therefore uses a process-independent hash (crc32)."""
    keys = ["alpha", "beta", "gamma", "delta", "epsilon"]
    items = [{"k": keys[i % 5], "v": float(i)} for i in range(200)]
    # many blocks => many distinct map worker processes
    ds = rd.from_items(items, parallelism=8)

    def count(group):
        return {"k": group["k"][:1],
                "n": np.asarray([len(np.asarray(group["v"]))])}

    out = ds.groupby("k").map_groups(count, num_partitions=4)
    rows = [(str(r["k"]), int(r["n"])) for r in out.take_all()]
    seen = {}
    for k, n in rows:
        assert k not in seen, f"key {k!r} split across partitions: {rows}"
        seen[k] = n
    assert seen == {k: 40 for k in keys}


def test_preprocessors(ray_start_local):
    """fit/transform layer (parity: ray/data/preprocessors/)."""
    from ray_tpu.data.preprocessors import (
        BatchMapper,
        Chain,
        LabelEncoder,
        MinMaxScaler,
        StandardScaler,
    )

    rows = [{"x": float(i), "y": float(i % 4), "label": ["a", "b", "c"][i % 3]}
            for i in range(64)]
    ds = rd.from_items(rows, parallelism=4)

    sc = StandardScaler(["x"]).fit(ds)
    out = np.concatenate([b["x"] for b in [
        __import__("ray_tpu").get(r) for r in sc.transform(ds).iter_block_refs()
    ]])
    assert abs(out.mean()) < 1e-6 and abs(out.std() - 1.0) < 1e-2

    mm = MinMaxScaler(["y"]).fit(ds)
    vals = [r["y"] for r in mm.transform(ds).take_all()]
    assert min(vals) == 0.0 and max(vals) == 1.0

    le = LabelEncoder("label").fit(ds)
    codes = {r["label"] for r in le.transform(ds).take_all()}
    assert codes == {0, 1, 2}
    assert list(le.classes_) == ["a", "b", "c"]

    chained = Chain(
        StandardScaler(["x"]),
        BatchMapper(lambda b: {**b, "x": np.asarray(b["x"]) * 2.0}),
    ).fit_transform(ds)
    xs = np.asarray([r["x"] for r in chained.take_all()])
    assert abs(xs.std() - 2.0) < 2e-2

    with pytest.raises(RuntimeError, match="must be fit"):
        StandardScaler(["x"]).transform(ds)


def test_dataset_stats(ray_start_local):
    """Per-op execution stats (parity: Dataset.stats / _internal/stats.py)."""
    ds = rd.range(100, parallelism=4).map_batches(lambda b: b)
    assert "not been executed" in ds.stats()
    _ = ds.take_all()
    s = ds.stats()
    assert "Read" in s and "MapBatches" in s
    assert "blocks=4" in s


def test_read_json_from_pandas_write_parquet(ray_start_local, tmp_path):
    pd = pytest.importorskip("pandas")
    pytest.importorskip("pyarrow")
    import json as _json

    # read_json (jsonl)
    p = tmp_path / "rows.jsonl"
    p.write_text("\n".join(_json.dumps({"a": i, "b": f"s{i}"})
                           for i in builtins_range(6)))
    ds = rd.read_json(str(p))
    assert ds.count() == 6
    assert sorted(r["a"] for r in ds.take_all()) == list(builtins_range(6))

    # from_pandas
    df = pd.DataFrame({"x": [1, 2, 3], "y": [1.0, 2.0, 3.0]})
    ds2 = rd.from_pandas(df)
    assert ds2.count() == 3 and ds2.take(1)[0]["y"] == 1.0

    # write_parquet roundtrip
    outdir = tmp_path / "out"
    files = rd.range(40, parallelism=3).write_parquet(str(outdir))
    assert len(files) == 3
    back = rd.read_parquet(str(outdir))
    assert sorted(r["id"] for r in back.take_all()) == list(builtins_range(40))


def test_actor_pool_stage_does_not_clobber_executor_cap(ray_start_local):
    """An actor-pool stage's in-flight cap is a PER-STAGE _bounded
    parameter: while its lazy stream drains, a concurrently-pulled
    task-based stage still sees the executor-wide max_in_flight (the old
    save/restore around the generator leaked the pool's cap to every
    other stage for the stage's whole lifetime)."""
    from ray_tpu.data.executor import (
        ActorPoolStrategy,
        MapBatchesOp,
        ReadOp,
        StreamingExecutor,
    )

    ex = StreamingExecutor(max_tasks_in_flight=8)
    ops = [
        ReadOp([(lambda i=i: {"id": np.array([i])}) for i in range(6)]),
        MapBatchesOp(
            fn=lambda b: {"id": b["id"] + 100},
            compute=ActorPoolStrategy(
                size=1, max_tasks_in_flight_per_actor=1
            ),
        ),
        MapBatchesOp(fn=lambda b: {"id": b["id"] * 2}),
    ]
    caps_seen = []
    stream = ex.execute(ops)
    import ray_tpu

    out = []
    for ref in stream:
        # mid-drain: the executor-wide cap must be untouched by the pool
        caps_seen.append(ex.max_in_flight)
        out.append(int(ray_tpu.get(ref)["id"][0]))
    assert sorted(out) == [(i + 100) * 2 for i in range(6)]
    assert set(caps_seen) == {8}, caps_seen
