"""Native object-transfer data plane (C++ sendfile daemon).

Parity: src/ray/object_manager/ — bulk object bytes move node-to-node
through the native daemon, not the Python RPC plane. The two raylets here
get SEPARATE shm sessions (real multi-host has no shared /dev/shm), so the
driver's get() must stream the object across through the daemon.
"""

import os
import shutil
import time
import uuid

import numpy as np
import pytest

from ray_tpu.core.object_store import native


def test_daemon_builds():
    assert native.build_transfer_server() is not None, "g++ toolchain expected"


@pytest.fixture
def split_session_cluster():
    import ray_tpu
    from ray_tpu.core.cluster_backend import (
        ProcessGroup,
        _session_tmp_dir,
        start_gcs,
        start_raylet,
    )

    ray_tpu.shutdown()
    # the chunked stream-plane pull (PR 15) outranks the native daemon by
    # default; this suite covers the DAEMON fallback, so pin it off in the
    # raylets spawned below
    saved = os.environ.get("RAY_TPU_PULL_CHUNKED_ENABLED")
    os.environ["RAY_TPU_PULL_CHUNKED_ENABLED"] = "0"
    session_a = f"s{uuid.uuid4().hex[:10]}"
    session_b = f"s{uuid.uuid4().hex[:10]}"
    procs = ProcessGroup(_session_tmp_dir(session_a))
    gcs = start_gcs(procs)
    start_raylet(procs, gcs, session_a, "node-a", num_cpus=1, num_tpus=0)
    start_raylet(procs, gcs, session_b, "node-b", num_cpus=1, num_tpus=0,
                 resources={"b": 1})
    # pin the driver to node-a's raylet/session — the producing task runs on
    # node-b (different session), forcing a genuine cross-node transfer
    ray_tpu.init(address=gcs, _node_name="node-a")
    try:
        yield ray_tpu, gcs
    finally:
        ray_tpu.shutdown()
        procs.shutdown()
        if saved is None:
            os.environ.pop("RAY_TPU_PULL_CHUNKED_ENABLED", None)
        else:
            os.environ["RAY_TPU_PULL_CHUNKED_ENABLED"] = saved
        from ray_tpu.core.object_store.shm_store import session_dir

        for s in (session_a, session_b):
            shutil.rmtree(session_dir(s), ignore_errors=True)


def test_cross_session_get_streams_through_native_daemon(split_session_cluster):
    ray, gcs = split_session_cluster
    ray.nodes()  # ensure registered

    @ray.remote(resources={"b": 1})
    def produce():
        return np.full(2_000_000, 9.0)  # 16 MB -> shm on node B

    ref = produce.remote()
    got = ray.get(ref, timeout=120)
    assert got.shape == (2_000_000,) and got[0] == 9.0

    # the bytes crossed through node B's native daemon
    from ray_tpu.api import _global_worker
    from ray_tpu.core import rpc as rpc_mod

    core = _global_worker().backend.core

    async def view():
        return await core.gcs.call("get_resource_view", timeout=30)

    nodes = core.io.run(view())
    served = None
    for v in nodes.values():
        p = v.get("transfer_port")
        if not p:
            continue
        st = native.stat("127.0.0.1", p, rpc_mod.get_auth_token() or "none")
        if st and st[1] > 0:
            served = st
    assert served is not None and served[1] >= 16_000_000, served
