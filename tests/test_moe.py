"""Mixture-of-experts layer + expert parallelism over the ep mesh axis.

Parity: SURVEY §2.10 expert parallelism (new TPU-native work, GShard-style
einsum dispatch — the reference has no TPU MoE).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def test_moe_top1_matches_dense_expert_reference():
    """With top_k=1 and unconstrained capacity, each token's output must
    equal its routed expert's MLP applied to it (numpy reference)."""
    from ray_tpu.ops.moe import moe_init, moe_mlp

    rng = jax.random.PRNGKey(0)
    B, S, D, F, E = 2, 8, 16, 32, 4
    params = jax.tree_util.tree_map(
        lambda p: p[0],  # layer 0
        moe_init(rng, 1, D, F, E, param_dtype=jnp.float32),
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (B, S, D), jnp.float32)

    y, aux = moe_mlp(x, params, top_k=1, capacity_factor=float(E),
                     dtype=jnp.float32)
    assert float(aux) > 0

    xt = np.asarray(x).reshape(-1, D)
    logits = xt @ np.asarray(params["router_w"])
    choice = logits.argmax(-1)
    ref = np.zeros_like(xt)
    for t in range(xt.shape[0]):
        e = choice[t]
        h = xt[t] @ np.asarray(params["fc_w"])[e] + np.asarray(params["fc_b"])[e]
        h = 0.5 * h * (1 + np.tanh(np.sqrt(2 / np.pi) * (h + 0.044715 * h**3)))
        ref[t] = h @ np.asarray(params["out_w"])[e] + np.asarray(params["out_b"])[e]
    np.testing.assert_allclose(
        np.asarray(y).reshape(-1, D), ref, rtol=2e-4, atol=2e-4
    )


def test_moe_capacity_drops_overflow_tokens():
    from ray_tpu.ops.moe import moe_mlp, moe_init

    B, S, D, F, E = 1, 8, 8, 16, 2
    params = jax.tree_util.tree_map(
        lambda p: p[0], moe_init(jax.random.PRNGKey(0), 1, D, F, E,
                                 param_dtype=jnp.float32)
    )
    # force every token to expert 0: positive inputs + an all-positive
    # expert-0 router column (logit_0 = 10*sum(x) > 0 = logit_1)
    params = dict(params)
    params["router_w"] = jnp.zeros((D, 2)).at[:, 0].set(10.0)
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(1), (B, S, D),
                                  jnp.float32)) + 0.1
    y, _ = moe_mlp(x, params, top_k=1, capacity_factor=0.5, dtype=jnp.float32)
    # capacity = ceil(8/2*0.5) = 2 slots on expert 0: later tokens dropped
    out = np.asarray(y)[0]
    nonzero = (np.abs(out) > 1e-8).any(axis=-1)
    assert nonzero[:2].all() and not nonzero[2:].any()


def test_moe_gpt2_trains_and_grads_flow():
    from ray_tpu.models import gpt2

    cfg = gpt2.gpt2_tiny(moe_experts=4, moe_top_k=2)
    params = gpt2.init(cfg, jax.random.PRNGKey(0))
    assert "moe" in params["blocks"]
    tok = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 512)
    tgt = jax.random.randint(jax.random.PRNGKey(2), (2, 128), 0, 512)
    loss, grads = jax.value_and_grad(
        lambda p: gpt2.loss_fn(p, tok, tgt, cfg)
    )(params)
    assert np.isfinite(float(loss))
    g = grads["blocks"]["moe"]["fc_w"]
    assert float(jnp.abs(g).sum()) > 0, "expert grads must flow"
    g_router = grads["blocks"]["moe"]["router_w"]
    assert float(jnp.abs(g_router).sum()) > 0, "router grads must flow"


def test_moe_expert_parallel_over_ep_mesh():
    """pjit the MoE train step over an ep=2 mesh: expert params shard on ep
    and a step executes (XLA inserts the dispatch all-to-all)."""
    from ray_tpu.models import gpt2
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.train.train_step import (
        default_optimizer,
        make_gpt2_train_step,
        synthetic_batch,
    )

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh (conftest sets XLA flags)")
    spec = mesh_lib.MeshSpec(dp=2, ep=2, tp=2)
    mesh = mesh_lib.make_mesh(spec, jax.devices()[:8])
    cfg = gpt2.gpt2_tiny(moe_experts=4, moe_top_k=2)
    bundle = make_gpt2_train_step(
        cfg, mesh=mesh, optimizer=default_optimizer(total_steps=10),
        rng=jax.random.PRNGKey(0),
    )
    fcw = bundle.state["params"]["blocks"]["moe"]["fc_w"]
    assert "ep" in str(fcw.sharding), f"experts not ep-sharded: {fcw.sharding}"
    batch = synthetic_batch(cfg, global_batch=4, seed=1)
    state, metrics = bundle.step_fn(bundle.state, batch)
    assert np.isfinite(float(metrics["loss"]))
