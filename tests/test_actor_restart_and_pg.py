"""Actor restart semantics + placement groups on the real cluster."""

import time

import pytest


@pytest.fixture(scope="module")
def ray_cluster2():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def test_actor_restart_after_crash(ray_cluster2):
    """max_restarts=1: kill the actor's worker process; the GCS must restart
    it (fresh state) and subsequent calls succeed (reference: actor.py:332
    max_restarts + GcsActorManager restart path)."""
    ray = ray_cluster2

    @ray.remote(max_restarts=1)
    class Phoenix:
        def __init__(self):
            self.calls = 0

        def bump(self):
            self.calls += 1
            return self.calls

        def crash(self):
            import os

            os._exit(42)

    p = Phoenix.remote()
    assert ray.get(p.bump.remote(), timeout=90) == 1
    assert ray.get(p.bump.remote(), timeout=90) == 2

    crash_ref = p.crash.remote()
    with pytest.raises(ray.exceptions.ActorError):
        ray.get(crash_ref, timeout=90)

    # post-restart: state reset, calls work again
    deadline = time.time() + 60
    val = None
    while time.time() < deadline:
        try:
            val = ray.get(p.bump.remote(), timeout=60)
            break
        except ray.exceptions.ActorError:
            time.sleep(1)
    assert val == 1, f"expected fresh state after restart, got {val}"


def test_actor_no_restart_stays_dead(ray_cluster2):
    ray = ray_cluster2

    @ray.remote(max_restarts=0)
    class Mortal:
        def crash(self):
            import os

            os._exit(1)

        def ping(self):
            return "pong"

    m = Mortal.remote()
    with pytest.raises(ray.exceptions.ActorError):
        ray.get(m.crash.remote(), timeout=90)
    with pytest.raises(ray.exceptions.ActorError):
        ray.get(m.ping.remote(), timeout=90)


def test_placement_group_reserve_and_run(ray_cluster2):
    ray = ray_cluster2
    from ray_tpu.util.placement_group import (
        PlacementGroupSchedulingStrategy,
        placement_group,
        remove_placement_group,
    )

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=60)

    @ray.remote(num_cpus=1)
    def inside():
        return "ran"

    ref = inside.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        ),
        placement_group=pg,
        placement_group_bundle_index=0,
    ).remote()
    assert ray.get(ref, timeout=90) == "ran"

    # PG holds both CPUs: a non-PG 1-CPU task must not find node resources
    avail = ray.available_resources()
    assert avail.get("CPU", 0) == 0, avail

    remove_placement_group(pg)
    time.sleep(2)
    assert ray.available_resources().get("CPU") == 2.0


def test_placement_group_infeasible_strict_spread(ray_cluster2):
    ray = ray_cluster2
    from ray_tpu.util.placement_group import placement_group

    # two bundles, one node → STRICT_SPREAD cannot place
    pg = placement_group(
        [{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD"
    )
    assert not pg.ready(timeout=5)


def test_pg_actor_draws_from_bundle_not_node(ray_cluster2):
    """Round-3 regression: an actor placed in a PG must consume the bundle's
    reservation, not node availability — double-booking starved every plain
    task while a WorkerGroup was alive (the Train+Data deadlock)."""
    ray = ray_cluster2
    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)

    @ray.remote
    class Holder:
        def ping(self):
            return 1

    a = Holder.options(
        placement_group=pg, placement_group_bundle_index=0, num_cpus=1
    ).remote()
    assert ray.get(a.ping.remote(), timeout=30) == 1

    # node had 2 CPUs; PG reserved 1; the actor lives INSIDE that bundle, so
    # 1 CPU must remain for plain tasks
    assert ray.available_resources().get("CPU", 0) == 1.0

    @ray.remote
    def plain():
        return "ok"

    assert ray.get(plain.remote(), timeout=60) == "ok"

    ray.kill(a)
    remove_placement_group(pg)
    time.sleep(2)
    assert ray.available_resources().get("CPU") == 2.0
