"""RPC auth handshake: unauthenticated peers must be rejected BEFORE any
frame is unpickled (pickle deserialization is the code-exec vector).
Advisor finding r1/r2; parity motivation: the reference runs gRPC inside a
trusted perimeter, our pickled frames must not assume one.
"""

import pickle
import socket
import struct

import pytest


@pytest.fixture
def cluster():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def _gcs_hostport(ray):
    from ray_tpu.api import _global_worker

    addr = _global_worker().backend.core.gcs_address
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def _frame(obj) -> bytes:
    from ray_tpu.core import rpc

    return rpc.encode_frame_bytes(obj)


def _preamble(token: bytes) -> bytes:
    from ray_tpu.core import rpc

    body = rpc._AUTH_MAGIC + token
    return struct.pack("<Q", len(body)) + body


def test_cluster_has_token(cluster):
    from ray_tpu.core import rpc

    assert rpc.get_auth_token(), "fresh cluster must mint a session token"


def test_unauthenticated_peer_rejected(cluster):
    host, port = _gcs_hostport(cluster)
    s = socket.create_connection((host, port), timeout=5)
    s.settimeout(5)
    # a well-formed RPC frame without the auth preamble
    s.sendall(_frame((0, 1, "get_nodes", {})))
    # server must close without ever responding
    assert s.recv(4096) == b"", "server must drop unauthenticated peers"
    s.close()


def test_wrong_token_rejected(cluster):
    host, port = _gcs_hostport(cluster)
    s = socket.create_connection((host, port), timeout=5)
    s.settimeout(5)
    s.sendall(_preamble(b"f" * 32))
    s.sendall(_frame((0, 1, "get_nodes", {})))
    assert s.recv(4096) == b"", "server must drop wrong-token peers"
    s.close()


def test_correct_token_accepted(cluster):
    from ray_tpu.core import rpc

    host, port = _gcs_hostport(cluster)
    s = socket.create_connection((host, port), timeout=10)
    s.settimeout(10)
    s.sendall(_preamble(rpc.get_auth_token().encode()))
    s.sendall(_frame((0, 1, "get_nodes", {})))
    hdr = s.recv(8)
    assert len(hdr) == 8, "authed peer must get a response"
    s.close()


def test_cross_process_driver_joins_via_token_file(cluster):
    """A second driver process with a clean environment joins by address
    alone: the token file written by start_gcs must authenticate it."""
    import os
    import subprocess
    import sys

    from ray_tpu.api import _global_worker

    addr = _global_worker().backend.core.gcs_address
    env = {k: v for k, v in os.environ.items() if k != "RAY_TPU_TOKEN"}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo
    code = (
        "import ray_tpu\n"
        f"ray_tpu.init(address='{addr}')\n"
        "@ray_tpu.remote\n"
        "def f(): return 41\n"
        "print('JOINED', ray_tpu.get(f.remote(), timeout=60) + 1)\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], env=env, capture_output=True,
        text=True, timeout=120,
    )
    assert "JOINED 42" in out.stdout, out.stdout + out.stderr


def test_protocol_version_mismatch_rejected():
    """A peer speaking a different wire-protocol rev is closed at the
    handshake with a logged reason — never unpickled (core/rpc.py
    PROTOCOL_VERSION gate)."""
    import asyncio
    import pickle

    from ray_tpu.core import rpc

    class H:
        def handle_ping(self, conn):
            return "pong"

    async def run():
        server = rpc.RpcServer(H(), host="127.0.0.1", port=0)
        await server.start()
        host, port = server.address.rsplit(":", 1)

        # wrong-rev preamble: connection must close without dispatch
        reader, writer = await asyncio.open_connection(host, int(port))
        bad = b"RAYTPU-AUTH999 " + (rpc.get_auth_token() or "").encode()
        writer.write(len(bad).to_bytes(8, "little") + bad)
        req = pickle.dumps((rpc.REQUEST, 1, "ping", {}))
        writer.write(len(req).to_bytes(8, "little") + req)
        await writer.drain()
        got = await reader.read(1)  # server closes -> EOF
        assert got == b""
        writer.close()

        # correct rev still works end-to-end
        conn = await rpc.connect(f"{host}:{port}")
        assert await conn.call("ping", timeout=10) == "pong"
        await conn.close()
        await server.close()

    asyncio.run(run())
