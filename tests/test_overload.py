"""End-to-end overload protection (PR 10): deadline propagation, admission
control, retry budgets with backoff, and replica circuit breaking.

The degradation plane's contract under saturating load: every rejected
request fails TYPED (BackPressureError / DeadlineExceededError /
RetryBudgetExhaustedError, HTTP 503 + Retry-After) within a bounded time,
no request hangs, deadline-expired work never executes, and total retries
stay inside the configured budget — all deterministic under a chaos seed.
"""

import http.client
import os
import subprocess
import sys
import tempfile
import threading
import time

import pytest


@pytest.fixture
def overload_config():
    """Mutate overload knobs for a test; restore afterwards."""
    from ray_tpu.core.config import _config

    fields = (
        "serve_circuit_failure_threshold", "serve_circuit_cooldown_s",
        "serve_circuit_slow_call_ms", "serve_retry_budget_ratio",
        "serve_retry_budget_min_tokens", "serve_retry_budget_burst",
        "serve_max_queued_requests", "retry_backoff_base_ms",
        "retry_backoff_max_ms",
    )
    saved = {f: getattr(_config, f) for f in fields}
    yield _config
    for f, v in saved.items():
        setattr(_config, f, v)


@pytest.fixture
def serve_local(overload_config):
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(local_mode=True)
    from ray_tpu import serve

    yield ray_tpu, serve
    serve.shutdown()
    ray_tpu.shutdown()


# ---------------------------------------------------------------- unit layer
def test_backoff_policy_growth_cap_and_determinism():
    from ray_tpu.testing import chaos
    from ray_tpu.util.backoff import BackoffPolicy

    p = BackoffPolicy(base_s=0.1, multiplier=2.0, max_s=0.8, jitter=0.0)
    assert [p.delay(n) for n in (1, 2, 3, 4, 5)] == [
        0.1, 0.2, 0.4, 0.8, 0.8  # capped at max_s
    ]
    # under an active chaos plan the jitter RNG seeds from the plan, so
    # two policies produce the SAME delay sequence (replayability)
    with chaos.plan(seed=42):
        a = BackoffPolicy(base_s=0.1, jitter=0.5)
        b = BackoffPolicy(base_s=0.1, jitter=0.5)
        seq_a = [a.delay(n) for n in range(1, 6)]
        seq_b = [b.delay(n) for n in range(1, 6)]
    assert seq_a == seq_b
    assert all(d >= 0 for d in seq_a)


def test_retry_budget_token_bucket():
    from ray_tpu.util.backoff import RetryBudget

    b = RetryBudget(ratio=0.5, min_tokens=2.0, burst=3.0)
    # cold bucket: min_tokens retries available
    assert b.try_spend() and b.try_spend()
    assert not b.try_spend()
    # each request deposits ratio, capped at burst
    for _ in range(100):
        b.note_request()
    assert b.tokens == 3.0
    assert b.try_spend() and b.try_spend() and b.try_spend()
    assert not b.try_spend()


def test_deadline_context_nesting_and_remaining():
    import ray_tpu
    from ray_tpu import tracing

    assert ray_tpu.remaining_time_s() is None
    now = time.time()
    with tracing.deadline_context(now + 10):
        r = ray_tpu.remaining_time_s()
        assert r is not None and 9 < r <= 10
        # a nested, LOOSER deadline cannot extend the budget
        with tracing.deadline_context(now + 100):
            assert ray_tpu.remaining_time_s() <= 10
        # a nested, tighter deadline wins
        with tracing.deadline_context(now + 1):
            assert ray_tpu.remaining_time_s() <= 1
        r = ray_tpu.remaining_time_s()
        assert r is not None and 9 < r <= 10
    assert ray_tpu.remaining_time_s() is None


def test_replica_max_ongoing_enforced_direct():
    """Replica-side defense in depth: once max_ongoing user requests are
    executing, the next is fast-rejected typed (several routers can
    overcommit one replica even when each respects its own cap)."""
    from ray_tpu import exceptions as exc
    from ray_tpu.serve.replica import ServeReplica

    release = threading.Event()
    started = threading.Event()

    def slow(x):
        started.set()
        release.wait(10)
        return x

    rep = ServeReplica(slow, (), {}, deployment_name="d", max_ongoing=1)
    t = threading.Thread(target=rep.handle_request, args=(1,))
    t.start()
    assert started.wait(5)
    with pytest.raises(exc.BackPressureError):
        rep.handle_request(2)
    release.set()
    t.join(10)
    assert rep.stats()["sheds"] == 1


def test_spool_sweep_reclaims_dead_reader_files():
    """ROADMAP item: cgraph_net spool files of a SIGKILLed stream reader
    are reclaimed by the session sweep instead of lingering."""
    from ray_tpu.core.transport import sweep_spool_dir

    d = tempfile.mkdtemp()
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    dead_pid, live_pid = p.pid, os.getpid()
    for name in (f"p{dead_pid}_chan_1", f"p{live_pid}_chan_2", "legacy_3"):
        with open(os.path.join(d, name), "wb") as f:
            f.write(b"x")
    old = time.time() - 60
    for name in os.listdir(d):
        os.utime(os.path.join(d, name), (old, old))
    # a FRESH dead-pid file survives (min_age grace for racing creations)
    with open(os.path.join(d, f"p{dead_pid}_chan_4"), "wb") as f:
        f.write(b"x")
    removed = sweep_spool_dir(d)
    left = sorted(os.listdir(d))
    assert removed == 1
    assert f"p{dead_pid}_chan_1" not in left
    assert f"p{live_pid}_chan_2" in left       # live reader keeps its spool
    assert "legacy_3" in left                  # un-tagged: age-out only
    assert f"p{dead_pid}_chan_4" in left


def test_transport_advertise_host_resolution():
    """Multi-host config: bind 0.0.0.0, advertise the raylet-host default
    unless transport_advertise_host overrides it."""
    from ray_tpu.core.config import _config
    from ray_tpu.core.transport import stream as tr

    saved = (_config.transport_bind_host, _config.transport_advertise_host,
             tr._default_advertise_host)
    try:
        _config.transport_advertise_host = ""
        lst = tr.StreamListener(host="127.0.0.1")
        assert lst.advertise_host == "127.0.0.1"
        lst.close()
        _config.transport_bind_host = "0.0.0.0"
        tr._default_advertise_host = ""
        lst = tr.StreamListener()
        assert lst.advertise_host == "127.0.0.1"  # no node default yet
        tr.set_default_advertise_host("10.1.2.3")
        assert lst.advertise_host == "10.1.2.3"
        _config.transport_advertise_host = "203.0.113.9"  # explicit wins
        assert lst.advertise_host == "203.0.113.9"
        lst.close()
    finally:
        (_config.transport_bind_host, _config.transport_advertise_host,
         tr._default_advertise_host) = saved


# ----------------------------------------------------------- deadline plane
def test_task_deadline_shed_pre_execution_local(serve_local):
    """An expired deadline sheds the task typed BEFORE user code runs —
    at the owner when already expired at submit, at the worker when it
    expired while queued."""
    ray_tpu, _ = serve_local
    from ray_tpu import exceptions as exc, tracing

    ran = []

    @ray_tpu.remote
    def f(x):
        ran.append(x)
        return x

    with tracing.deadline_context(time.time() - 0.1):
        ref = f.remote(1)
    with pytest.raises(exc.DeadlineExceededError):
        ray_tpu.get(ref, timeout=10)
    assert 1 not in ran

    @ray_tpu.remote
    class A:
        def m(self, x):
            ran.append(x)
            return x

    a = A.remote()
    with tracing.deadline_context(time.time() - 0.1):
        ref = a.m.remote(2)
    with pytest.raises(exc.DeadlineExceededError):
        ray_tpu.get(ref, timeout=10)
    assert 2 not in ran


def test_remaining_time_s_visible_inside_task(serve_local):
    ray_tpu, _ = serve_local
    from ray_tpu import tracing

    @ray_tpu.remote
    def budget():
        return ray_tpu.remaining_time_s()

    assert ray_tpu.get(budget.remote(), timeout=10) is None
    with tracing.deadline_context(time.time() + 30):
        r = ray_tpu.get(budget.remote(), timeout=10)
    assert r is not None and 0 < r <= 30


def test_serve_deadline_propagates_into_replica(serve_local):
    """The deadline minted at the handle is visible to user code on the
    replica (remaining_time_s) and bounded by request_timeout_s."""
    ray_tpu, serve = serve_local

    @serve.deployment(request_timeout_s=7.5)
    class Budgeted:
        def __call__(self, _):
            import ray_tpu as rt

            return rt.remaining_time_s()

    h = serve.run(Budgeted.bind())
    r = ray_tpu.get(h.remote(0), timeout=30)
    assert r is not None and 0 < r <= 7.5
    serve.delete("Budgeted")


# --------------------------------------------------------- admission control
def test_serve_admission_control_sheds_typed(serve_local):
    """max_ongoing=1 + max_queued=2: a 8-wide concurrent burst admits 3
    (1 executing + 2 queued) and sheds the rest typed in ~microseconds."""
    ray_tpu, serve = serve_local
    from ray_tpu import exceptions as exc

    @serve.deployment(max_ongoing_requests=1, max_queued_requests=2,
                      request_timeout_s=30)
    class Slow:
        def __call__(self, x):
            time.sleep(0.4)
            return x

    h = serve.run(Slow.bind())
    assert ray_tpu.get(h.remote(-1), timeout=30) == -1
    out, lock = [], threading.Lock()

    def fire(i):
        t0 = time.perf_counter()
        try:
            v = ray_tpu.get(h.remote(i), timeout=30)
            res = ("ok", v, time.perf_counter() - t0)
        except exc.BackPressureError:
            res = ("shed", i, time.perf_counter() - t0)
        with lock:
            out.append(res)

    threads = [threading.Thread(target=fire, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    sheds = [o for o in out if o[0] == "shed"]
    oks = [o for o in out if o[0] == "ok"]
    assert len(out) == 8 and len(oks) == 3 and len(sheds) == 5, out
    # shed path is fast (never queued behind the work)
    assert max(o[2] for o in sheds) < 0.5
    # metrics: sheds counted per deployment
    from ray_tpu.util.metrics import get_registry

    snap = {s["name"]: s for s in get_registry().collect()}
    pts = snap["serve_shed_total"]["points"]
    assert pts.get((("deployment", "Slow"),), 0) >= 5
    serve.delete("Slow")


def test_serve_deadline_expired_in_router_queue_sheds(serve_local):
    """A queued request whose deadline expires sheds typed at the router —
    the replica NEVER runs it (counter-asserted)."""
    ray_tpu, serve = serve_local
    from ray_tpu import exceptions as exc

    ran = []

    @serve.deployment(max_ongoing_requests=1, max_queued_requests=8,
                      request_timeout_s=0.6)
    class Busy:
        def __call__(self, x):
            ran.append(x)
            time.sleep(0.35)
            return x

    h = serve.run(Busy.bind())
    assert ray_tpu.get(h.remote(-1), timeout=30) == -1
    out, lock = [], threading.Lock()

    def fire(i):
        try:
            v = ray_tpu.get(h.remote(i), timeout=30)
            res = ("ok", v)
        except exc.DeadlineExceededError:
            res = ("deadline", i)
        with lock:
            out.append(res)

    threads = [threading.Thread(target=fire, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    shed = {o[1] for o in out if o[0] == "deadline"}
    assert shed, out                      # some requests outqueued their SLO
    assert not (shed & set(ran))          # ...and never executed
    from ray_tpu.util.metrics import get_registry

    snap = {s["name"]: s for s in get_registry().collect()}
    pts = snap["serve_deadline_expired_total"]["points"]
    assert pts.get((("deployment", "Busy"),), 0) >= len(shed)
    serve.delete("Busy")


def test_routing_table_carries_admission_bounds(serve_local):
    ray_tpu, serve = serve_local

    @serve.deployment(max_ongoing_requests=3, max_queued_requests=17)
    def f(x):
        return x

    h = serve.run(f)
    assert ray_tpu.get(h.remote(1), timeout=30) == 1
    router = h._router
    assert router.max_ongoing_for("f") == 3
    assert router.max_queued_for("f") == 17
    serve.delete("f")


# ------------------------------------------------------------ chaos scenarios
@pytest.mark.chaos(timeout=120)
def test_circuit_breaker_slow_replica_trips_fails_over_recovers(serve_local):
    """Acceptance (a): a chaos slow-replica injection trips the breaker,
    traffic fails over to the healthy replica, and once the cooldown
    passes a half-open probe restores the ejected replica."""
    ray_tpu, serve = serve_local
    from ray_tpu.testing import chaos

    cfg = __import__("ray_tpu.core.config", fromlist=["_config"])._config
    cfg.serve_circuit_failure_threshold = 2
    cfg.serve_circuit_cooldown_s = 0.6
    cfg.serve_circuit_slow_call_ms = 100.0

    @serve.deployment(num_replicas=2, max_ongoing_requests=4,
                      request_timeout_s=10)
    class Echo:
        def __call__(self, x):
            return x

    h = serve.run(Echo.bind())
    assert ray_tpu.get(h.remote(0), timeout=30) == 0
    router = h._router
    keys = [r._actor_id.binary() for r in router.wait_for_replicas("Echo")]
    victim = keys[0]

    with chaos.plan(seed=7).slow_replica(
        match=victim.hex(), delay_s=0.25, times=2
    ) as plan:
        for i in range(20):
            ray_tpu.get(h.remote(i), timeout=30)
        states = [router.circuit_state("Echo", k) for k in keys]
        assert states == ["open", "closed"], states
        # controller was told (operators see the ejection)
        st = serve.status()
        assert st["Echo"]["circuit"], st
        # traffic keeps flowing (failed over) while the breaker is open
        assert ray_tpu.get(h.remote(99), timeout=30) == 99
        # cooldown passes; the injection budget (times=2) is spent, so the
        # half-open probe hits a fast replica again and CLOSES the breaker
        time.sleep(0.8)
        for i in range(20):
            ray_tpu.get(h.remote(100 + i), timeout=30)
        deadline = time.time() + 5
        while time.time() < deadline and \
                router.circuit_state("Echo", victim) != "closed":
            ray_tpu.get(h.remote(0), timeout=30)
            time.sleep(0.05)
        assert router.circuit_state("Echo", victim) == "closed"
        # exactly the two planned injections fired (deterministic)
        assert len(plan.events()) == 2
    st = serve.status()
    assert st["Echo"]["circuit"] == {}, st
    serve.delete("Echo")


@pytest.mark.chaos(timeout=120)
def test_retry_budget_storm_typed_and_bounded(serve_local):
    """Acceptance (c): under a seeded replica-kill storm, retries stop at
    the budget (counter-asserted), every caller gets a TYPED error within
    a bounded time, and a same-seed replay reproduces the kill sequence."""
    ray_tpu, serve = serve_local
    from ray_tpu import exceptions as exc
    from ray_tpu.testing import chaos

    cfg = __import__("ray_tpu.core.config", fromlist=["_config"])._config
    cfg.serve_retry_budget_min_tokens = 2.0
    cfg.serve_retry_budget_ratio = 0.0    # no refill: exactly 2 retries
    cfg.retry_backoff_base_ms = 10.0      # keep the test fast
    cfg.retry_backoff_max_ms = 50.0

    def run_storm(seed):
        @serve.deployment(num_replicas=2, max_ongoing_requests=4,
                          request_timeout_s=10)
        class Victim:
            def __call__(self, x):
                return x

        h = serve.run(Victim.bind())
        assert ray_tpu.get(h.remote(0), timeout=30) == 0
        router = h._router
        with chaos.plan(seed=seed).kill_actor(
            match="ServeReplica.handle_request", repeat=True, times=12
        ) as plan:
            outcomes = []
            t0 = time.perf_counter()
            for i in range(8):
                try:
                    outcomes.append(("ok", ray_tpu.get(h.remote(i),
                                                       timeout=20)))
                except exc.RetryBudgetExhaustedError:
                    outcomes.append(("budget", i))
                except exc.RayTpuError as e:
                    outcomes.append((type(e).__name__, i))
            elapsed = time.perf_counter() - t0
            events = [(e["point"], e["action"], e["count"])
                      for e in plan.events()]
        serve.delete("Victim")
        return outcomes, router.retry_count, elapsed, events

    outcomes, retries, elapsed, events = run_storm(11)
    # bounded: no hangs (8 doomed requests resolve fast), typed outcomes
    assert elapsed < 60
    assert retries <= 2, retries
    assert any(o[0] == "budget" for o in outcomes), outcomes
    assert all(o[0] in ("ok", "budget", "ActorDiedError")
               for o in outcomes), outcomes
    from ray_tpu.util.metrics import get_registry

    snap = {s["name"]: s for s in get_registry().collect()}
    pts = snap["serve_retry_budget_exhausted_total"]["points"]
    assert pts.get((("deployment", "Victim"),), 0) >= 1
    # seeded replay: the same plan replays the same injection sequence.
    # The total kill COUNT depends on how many replacements the 1s
    # reconcile ticker spun up inside the window (wall-clock), so the
    # determinism claim is the common prefix — same points, same actions,
    # same per-rule counts, in the same order — plus the same bounded
    # outcome: budget exhausted, retries within it.
    outcomes2, retries2, _, events2 = run_storm(11)
    n = min(len(events), len(events2))
    assert n >= 3
    assert events2[:n] == events[:n]
    assert retries2 <= 2
    assert any(o[0] == "budget" for o in outcomes2), outcomes2


# ----------------------------------------------------------------- HTTP edge
def test_proxy_503_retry_after_and_client_timeout_header(serve_local):
    """Acceptance: overflow → HTTP 503 with Retry-After on the unary path;
    the client's X-Request-Timeout-S header tightens the deadline."""
    ray_tpu, serve = serve_local

    @serve.deployment(max_ongoing_requests=1, max_queued_requests=1,
                      request_timeout_s=5)
    class Busy:
        def __call__(self, x):
            time.sleep(0.4)
            return x

    serve.run(Busy.bind(), http=True)
    addr = serve.http_address()
    host, port = addr.replace("http://", "").split(":")

    def call(path, body=None, headers=None):
        c = http.client.HTTPConnection(host, int(port), timeout=30)
        c.request("POST" if body else "GET", path, body=body,
                  headers=headers or {})
        r = c.getresponse()
        return r.status, dict(r.getheaders()), r.read()

    status, _, _ = call("/Busy", body=b"1")  # warm routing table + replica
    assert status == 200
    results, lock = [], threading.Lock()

    def fire(i):
        st, hdr, data = call("/Busy", body=b"7")
        with lock:
            results.append((st, hdr.get("Retry-After"), data))

    threads = [threading.Thread(target=fire, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    codes = sorted(st for st, _, _ in results)
    assert 200 in codes and 503 in codes, results
    for st, retry_after, data in results:
        if st == 503:
            assert retry_after == "1"
            assert b"BackPressureError" in data or b"capacity" in data
    # client header deadline: ask for an impossible 1 ms budget while a
    # slow request occupies the replica → typed 503, not a hang or a 500
    blocker = threading.Thread(target=call, args=("/Busy",), kwargs={"body": b"9"})
    blocker.start()
    time.sleep(0.05)
    st, hdr, data = call("/Busy", body=b"8",
                         headers={"X-Request-Timeout-S": "0.001"})
    blocker.join(30)
    assert st == 503, (st, data)
    assert hdr.get("Retry-After") == "1"
    serve.delete("Busy")
