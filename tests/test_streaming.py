"""Streaming generators (ray_tpu/streaming/): push-based ObjectRefGenerator
outputs with backpressure, typed failure semantics, and the serve rebuild.

Covers the acceptance surface of the subsystem:
- num_returns="streaming" for tasks AND actor methods, local + cluster;
- backpressure: the producer is provably blocked until the consumer drains;
- a mid-stream user exception surfaces on the exact item that raised;
- a producer killed mid-stream (chaos-injected) raises a typed error on the
  consumer's next item instead of hanging;
- serve handle.stream() and the HTTP chunked path run on the generator
  subsystem with ZERO per-chunk polling RPCs.
"""

import asyncio
import json
import time

import pytest

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.testing import chaos


# --------------------------------------------------------------------------
# local mode
# --------------------------------------------------------------------------

def test_local_task_stream_roundtrip(ray_start_local):
    @ray_tpu.remote
    def squares(n):
        for i in range(n):
            yield i * i

    gen = squares.options(num_returns="streaming").remote(6)
    assert isinstance(gen, ray_tpu.ObjectRefGenerator)
    refs = list(gen)
    assert all(isinstance(r, ray_tpu.ObjectRef) for r in refs)
    assert [ray_tpu.get(r) for r in refs] == [i * i for i in range(6)]
    # the end is typed: a drained generator keeps raising StopIteration
    with pytest.raises(StopIteration):
        next(gen)


def test_local_actor_stream_sync_and_async(ray_start_local):
    @ray_tpu.remote
    class Tokens:
        @ray_tpu.method(num_returns="streaming")
        def generate(self, prompt, n):
            for i in range(n):
                yield f"{prompt}-{i}"

    a = Tokens.remote()
    out = [ray_tpu.get(r) for r in a.generate.remote("tok", 4)]
    assert out == ["tok-0", "tok-1", "tok-2", "tok-3"]

    async def drain():
        vals = []
        async for ref in a.generate.remote("async", 3):
            vals.append(ray_tpu.get(ref))
        return vals

    assert asyncio.run(drain()) == ["async-0", "async-1", "async-2"]


def test_local_midstream_exception_on_exact_item(ray_start_local):
    @ray_tpu.remote
    def flaky(n):
        for i in range(n):
            if i == 3:
                raise ValueError("boom at 3")
            yield i

    gen = flaky.options(num_returns="streaming").remote(10)
    got = []
    with pytest.raises(ValueError, match="boom at 3"):
        for ref in gen:
            got.append(ray_tpu.get(ref))
    # every item before the raising one was delivered
    assert got == [0, 1, 2]


def test_local_non_generator_is_typed_error(ray_start_local):
    @ray_tpu.remote
    def scalar():
        return 42

    gen = scalar.options(num_returns="streaming").remote()
    with pytest.raises(TypeError, match="requires a generator"):
        ray_tpu.get(next(gen))


def test_local_backpressure_blocks_producer(ray_start_local):
    progress = []  # local mode: producer shares memory with the test

    @ray_tpu.remote
    class Producer:
        def produce(self, n):
            for i in range(n):
                progress.append(i)
                yield i

    p = Producer.remote()
    gen = p.produce.options(
        num_returns="streaming", generator_backpressure_num_objects=2
    ).remote(20)
    # consumer does NOT drain: the producer must stall inside the window
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and len(progress) < 2:
        time.sleep(0.05)
    stalled = len(progress)
    time.sleep(0.3)  # provably blocked: no further progress while undrained
    assert len(progress) == stalled
    assert 1 <= stalled <= 3, stalled  # window + at most one in-flight item
    # draining releases the producer and the stream completes
    assert [ray_tpu.get(r) for r in gen] == list(range(20))
    assert len(progress) == 20


def test_local_chaos_producer_kill_raises_typed(ray_start_local):
    @ray_tpu.remote
    class Src:
        def chunks(self, n):
            for i in range(n):
                yield i

    a = Src.remote()
    with chaos.plan(seed=3).kill_stream_producer(match="chunks", after_items=3):
        gen = a.chunks.options(num_returns="streaming").remote(10)
        got = []
        with pytest.raises(exc.ActorDiedError):
            for ref in gen:
                got.append(ray_tpu.get(ref))
        assert got == [0, 1]  # items produced before the kill stay readable


def test_objectref_generator_not_serializable(ray_start_local):
    @ray_tpu.remote
    def g():
        yield 1

    gen = g.options(num_returns="streaming").remote()
    import cloudpickle

    with pytest.raises(Exception, match="not serializable"):
        cloudpickle.dumps(gen)
    list(gen)


# --------------------------------------------------------------------------
# cluster mode (one shared cluster for the whole module: init is seconds)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def streaming_cluster():
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def test_cluster_task_stream_with_large_items(streaming_cluster):
    import numpy as np

    @ray_tpu.remote
    def mixed(n):
        for i in range(n):
            if i % 2:
                # > max_direct_call_object_size: rides the shm store, the
                # owner reads it through the location plane (not the RPC)
                yield np.full((200_000,), i, dtype=np.float64)
            else:
                yield i

    gen = mixed.options(num_returns="streaming").remote(4)
    vals = [ray_tpu.get(r) for r in gen]
    assert vals[0] == 0 and vals[2] == 2
    assert vals[1].shape == (200_000,) and vals[1][0] == 1.0
    assert vals[3][-1] == 3.0


def test_cluster_actor_stream_and_midstream_error(streaming_cluster):
    @ray_tpu.remote
    class Tokens:
        @ray_tpu.method(num_returns="streaming")
        def generate(self, n, fail_at=None):
            for i in range(n):
                if fail_at is not None and i == fail_at:
                    raise RuntimeError(f"boom at {i}")
                yield i * 10

    a = Tokens.remote()
    assert [ray_tpu.get(r) for r in a.generate.remote(5)] == [
        0, 10, 20, 30, 40
    ]
    gen = a.generate.remote(5, fail_at=2)
    got = []
    with pytest.raises(RuntimeError, match="boom at 2"):
        for ref in gen:
            got.append(ray_tpu.get(ref))
    assert got == [0, 10]


def test_cluster_backpressure_blocks_producer(streaming_cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

        def value(self):
            return self.n

    @ray_tpu.remote
    class Producer:
        def produce(self, n, counter):
            for i in range(n):
                ray_tpu.get(counter.inc.remote())
                yield i

    c = Counter.remote()
    p = Producer.remote()
    gen = p.produce.options(
        num_returns="streaming", generator_backpressure_num_objects=2
    ).remote(15, c)
    # wait until the producer's side-channel counter stops moving
    last, stable_since = -1, time.monotonic()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        cur = ray_tpu.get(c.value.remote(), timeout=30)
        if cur != last:
            last, stable_since = cur, time.monotonic()
        elif cur > 0 and time.monotonic() - stable_since > 1.0:
            break
        time.sleep(0.1)
    assert 1 <= last <= 3, last  # provably blocked inside the window
    assert [ray_tpu.get(r) for r in gen] == list(range(15))
    assert ray_tpu.get(c.value.remote(), timeout=30) == 15


def test_cluster_serialized_passthrough_deferred(streaming_cluster):
    """Serve-failover satellite: the deferred ref accepts pre-serialized
    bytes and as_serialized_future hands back bytes — the success relay
    never decodes + re-encodes the replica response."""
    from ray_tpu.api import _global_worker

    backend = _global_worker().backend
    src = ray_tpu.put({"payload": list(range(10))})
    data = backend.as_serialized_future(src).result(timeout=30)
    assert isinstance(data, (bytes, memoryview))
    ref, fulfill = backend.create_deferred()
    fulfill(serialized=data)
    assert ray_tpu.get(ref, timeout=30) == {"payload": list(range(10))}
    # error path still types correctly
    ref2, fulfill2 = backend.create_deferred()
    fulfill2(error=exc.ActorDiedError(None, "relay"))
    with pytest.raises(exc.ActorDiedError):
        ray_tpu.get(ref2, timeout=30)


# --------------------------------------------------------------------------
# serve on the generator subsystem
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def serve_streaming(streaming_cluster):
    from ray_tpu import serve

    yield streaming_cluster, serve
    serve.shutdown()


def test_serve_stream_push_no_polling(serve_streaming):
    ray, serve = serve_streaming

    @serve.deployment(name="gen", route_prefix="/gen", request_timeout_s=30)
    def gen(payload):
        def chunks():
            for i in range(int(payload["n"])):
                yield {"i": i, "sq": i * i}
        return chunks()

    handle = serve.run(gen, http=True)
    # deployment-level timeout propagated to the handle and routing table
    assert handle._timeout() == 30
    assert handle._router.timeout_for("gen") == 30
    assert handle.options(timeout_s=5)._timeout() == 5

    out = list(handle.stream({"n": 5}))
    assert out == [{"i": i, "sq": i * i} for i in range(5)]

    # HTTP chunked transfer through the proxy, still push-based
    import http.client

    addr = serve.http_address().replace("http://", "")
    host, port = addr.rsplit(":", 1)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        conn = http.client.HTTPConnection(host, int(port), timeout=60)
        conn.request("POST", "/gen", body=json.dumps({"n": 4}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status == 200:
            break
        resp.read()
        conn.close()
        time.sleep(0.25)
    assert resp.status == 200
    assert resp.headers.get("Transfer-Encoding") == "chunked"
    lines = [json.loads(l) for l in resp.read().decode().strip().split("\n")]
    assert lines == [{"i": i, "sq": i * i} for i in range(4)]
    conn.close()

    # ZERO per-chunk polling RPCs reached any replica
    from ray_tpu.serve import api as serve_api

    table = ray.get(
        serve_api._local["controller"].routing_table.remote(-1), timeout=30
    )
    for replica in table["deployments"]["gen"]:
        stats = ray.get(replica.stats.remote(), timeout=30)
        assert stats["legacy_polls"] == 0, stats
    serve.delete("gen")


def test_serve_stream_polling_fallback_still_works(serve_streaming):
    ray, serve = serve_streaming

    @serve.deployment(name="legacy")
    def legacy(payload):
        def chunks():
            for i in range(int(payload["n"])):
                yield i
        return chunks()

    handle = serve.run(legacy)
    assert list(handle.stream_polling({"n": 4})) == [0, 1, 2, 3]
    serve.delete("legacy")


def test_serve_stream_midstream_error_surfaces(serve_streaming):
    ray, serve = serve_streaming

    @serve.deployment(name="flaky_stream")
    def flaky(payload):
        def chunks():
            yield 1
            yield 2
            raise RuntimeError("stream blew up")
        return chunks()

    handle = serve.run(flaky)
    got = []
    with pytest.raises(RuntimeError, match="stream blew up"):
        for chunk in handle.stream({}):
            got.append(chunk)
    assert got == [1, 2]
    serve.delete("flaky_stream")


# --------------------------------------------------------------------------
# legacy next_chunk reaper edge (satellite): eviction must raise, not
# silently truncate, even for sids the bounded reap ledger forgot
# --------------------------------------------------------------------------

def test_next_chunk_lru_eviction_always_raises(monkeypatch):
    from ray_tpu.serve import replica as replica_mod
    from ray_tpu.serve.replica import ServeReplica

    def streamer(n):
        def gen():
            for i in range(n):
                yield i
        return gen()

    r = ServeReplica(streamer, (), {})
    monkeypatch.setattr(replica_mod, "MAX_STREAMS", 2)

    sid1 = r.handle_request(5)["__serve_stream__"]
    assert r.next_chunk(sid1) == {"done": False, "value": 0}
    sid2 = r.handle_request(5)["__serve_stream__"]
    sid3 = r.handle_request(5)["__serve_stream__"]  # evicts sid1 at the cap
    assert sid1 not in r._streams
    # the evicted, undrained stream raises on the consumer's next poll
    with pytest.raises(RuntimeError, match="reaped"):
        r.next_chunk(sid1)
    # even a sid the bounded reap ledger has forgotten must raise — silent
    # truncation is never an option for unknown sids
    r._reaped_set.discard(sid1)
    with pytest.raises(RuntimeError, match="unknown"):
        r.next_chunk(sid1)
    # a cleanly drained sid reports benign done on a duplicate poll
    while not r.next_chunk(sid3).get("done"):
        pass
    assert r.next_chunk(sid3) == {"done": True}
    # never-registered sids raise instead of lying about completion
    with pytest.raises(RuntimeError, match="unknown"):
        r.next_chunk("no-such-sid")
    assert r.next_chunk(sid2)["value"] == 0


# --------------------------------------------------------------------------
# chaos: producer SIGKILL mid-stream in cluster mode. LAST in the module —
# the plan must be active before daemons/workers spawn, so this test owns
# its cluster (and tears down the module-shared one).
# --------------------------------------------------------------------------

@pytest.mark.chaos(timeout=180)
def test_cluster_chaos_producer_kill_raises_typed():
    """A chaos-SIGKILLed producer worker fails the stream with a typed
    error on the consumer's next item — never a hang or silent end. The
    cluster starts INSIDE the plan so every daemon/worker inherits it."""
    ray_tpu.shutdown()

    @ray_tpu.remote
    class Src:
        def chunks(self, n):
            for i in range(n):
                yield i

    with chaos.plan(seed=7).kill_stream_producer(match="chunks",
                                                 after_items=3) as plan:
        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            a = Src.remote()
            gen = a.chunks.options(
                num_returns="streaming",
                generator_backpressure_num_objects=1,  # kill mid-iteration
            ).remote(100)
            got = []
            with pytest.raises(exc.ActorDiedError):
                while True:
                    got.append(ray_tpu.get(gen.next_ref(60), timeout=60))
            assert got == [0, 1]  # produced-before-kill items stay readable
            # the SIGKILL really fired in the producer worker process
            fired = [e for e in plan.events() if e["point"] == "stream.yield"]
            assert fired and fired[0]["action"] == "kill"
        finally:
            ray_tpu.shutdown()
