"""Runtime environments: env_vars, working_dir, py_modules.

Parity: python/ray/_private/runtime_env/ — the driver packages local dirs
through the GCS KV and workers stage+apply them around task execution
(runtime_env.py WorkerEnvApplier). Pip installs are out of scope by design.
"""

import os
import textwrap

import pytest


@pytest.fixture
def cluster():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def test_env_vars_applied_and_reset(cluster):
    ray = cluster

    @ray.remote(runtime_env={"env_vars": {"MY_FLAG": "on"}})
    def with_env():
        return os.environ.get("MY_FLAG")

    @ray.remote
    def without_env():
        return os.environ.get("MY_FLAG")

    assert ray.get(with_env.remote(), timeout=60) == "on"
    # pooled workers are reused: the env must not leak into envless tasks
    assert ray.get(without_env.remote(), timeout=60) is None


def test_py_modules_importable_in_worker(cluster, tmp_path):
    ray = cluster
    pkg = tmp_path / "mymod"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "helper.py").write_text(
        textwrap.dedent(
            """
            def triple(x):
                return 3 * x
            """
        )
    )

    @ray.remote(runtime_env={"py_modules": [str(tmp_path)]})
    def use_module(x):
        from mymod.helper import triple

        return triple(x)

    assert ray.get(use_module.remote(5), timeout=60) == 15


def test_working_dir_staged_and_cwd_set(cluster, tmp_path):
    ray = cluster
    (tmp_path / "data.txt").write_text("hello-workdir")

    @ray.remote(runtime_env={"working_dir": str(tmp_path)})
    def read_data():
        with open("data.txt") as f:
            return f.read()

    assert ray.get(read_data.remote(), timeout=60) == "hello-workdir"


def test_actor_runtime_env_applies_for_life(cluster):
    ray = cluster

    @ray.remote(runtime_env={"env_vars": {"ACTOR_FLAG": "yes"}})
    class EnvActor:
        def read(self):
            return os.environ.get("ACTOR_FLAG")

    a = EnvActor.remote()
    assert ray.get(a.read.remote(), timeout=60) == "yes"
    assert ray.get(a.read.remote(), timeout=60) == "yes"


def test_unknown_runtime_env_key_rejected(cluster):
    ray = cluster
    with pytest.raises(ValueError, match="unsupported runtime_env"):

        @ray.remote(runtime_env={"pip": ["torch"]})
        def f():
            return 1

        f.remote()


def test_timeline_exports_chrome_trace(cluster, tmp_path):
    """ray_tpu.timeline pairs RUNNING->FINISHED GCS task events into
    chrome-trace complete events (parity: ray.timeline)."""
    import json
    import time

    ray = cluster

    @ray.remote
    def work(ms):
        time.sleep(ms / 1000)
        return ms

    ray.get([work.remote(30) for _ in range(4)], timeout=60)
    time.sleep(1.5)  # task-event flush loop period
    out = tmp_path / "trace.json"
    events = ray.timeline(str(out))
    mine = [e for e in events if e["name"] == "work"]
    assert len(mine) >= 4
    assert all(e["ph"] == "X" and e["dur"] >= 25_000 for e in mine)
    assert json.loads(out.read_text())
