"""Compiled execution graphs (ray_tpu/cgraph/): compile → repeated execute
correctness (linear, fan-out/fan-in, actor-method chains, multi-output),
error propagation, teardown, and overlap bounded by channel capacity.

Most tests run in local mode (in-process channels); the cluster-mode test
exercises the shared-memory ring-buffer channels end to end.
"""

import time

import pytest


def _make_adders(ray_tpu, *ks):
    @ray_tpu.remote
    class Adder:
        def __init__(self, k):
            self.k = k

        def add(self, x):
            return x + self.k

        def add2(self, x, y):
            return x + y + self.k

        def boom(self, x):
            raise ValueError(f"boom:{x}")

        def slow(self, x):
            time.sleep(0.3)
            return x

    return [Adder.remote(k) for k in ks]


def test_linear_chain_repeated_execute(ray_start_local):
    import ray_tpu
    from ray_tpu.dag import InputNode

    a, b, c = _make_adders(ray_tpu, 1, 10, 100)
    with InputNode() as inp:
        dag = c.add.bind(b.add.bind(a.add.bind(inp)))

    # interpreted and compiled agree
    assert ray_tpu.get(dag.execute(0)) == 111

    compiled = dag.experimental_compile(max_in_flight=4)
    try:
        for i in range(20):
            assert compiled.execute(i).get(timeout=10) == 111 + i
    finally:
        compiled.teardown()


def test_overlapped_pipelined_execution(ray_start_local):
    import ray_tpu
    from ray_tpu.dag import InputNode

    a, b = _make_adders(ray_tpu, 1, 10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile(max_in_flight=8)
    try:
        refs = [compiled.execute(i, timeout=10) for i in range(8)]
        assert [r.get(timeout=10) for r in refs] == [11 + i for i in range(8)]
        # out-of-order get: later ref first, earlier ref still correct
        r0 = compiled.execute(100)
        r1 = compiled.execute(200)
        assert r1.get(timeout=10) == 211
        assert r0.get(timeout=10) == 111
        # repeated get returns the cached result
        assert r0.get(timeout=10) == 111
    finally:
        compiled.teardown()


def test_fan_out_fan_in_and_multi_arg(ray_start_local):
    import ray_tpu
    from ray_tpu.dag import InputNode

    a, b, j = _make_adders(ray_tpu, 1, 10, 0)
    with InputNode() as inp:
        left = a.add.bind(inp)
        right = b.add.bind(inp)
        dag = j.add2.bind(left, right)
    compiled = dag.experimental_compile()
    try:
        for i in range(5):
            # (i+1) + (i+10) + 0
            assert compiled.execute(i).get(timeout=10) == 2 * i + 11
    finally:
        compiled.teardown()


def test_function_nodes_and_mixed_graph(ray_start_local):
    import ray_tpu
    from ray_tpu.dag import InputNode

    (a,) = _make_adders(ray_tpu, 5)

    @ray_tpu.remote
    def double(x):
        return 2 * x

    with InputNode() as inp:
        dag = double.bind(a.add.bind(double.bind(inp)))
    compiled = dag.experimental_compile()
    try:
        for i in range(4):
            assert compiled.execute(i).get(timeout=10) == 2 * (2 * i + 5)
    finally:
        compiled.teardown()


def test_multi_output_and_input_attributes(ray_start_local):
    import ray_tpu
    from ray_tpu.dag import InputNode, MultiOutputNode

    a, b = _make_adders(ray_tpu, 1, 10)
    with InputNode() as inp:
        n1 = a.add.bind(inp[0])
        n2 = b.add.bind(inp[1])
        dag = MultiOutputNode([n1, n2])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(7, 70).get(timeout=10) == [8, 80]
        assert compiled.execute(1, 2).get(timeout=10) == [2, 12]
    finally:
        compiled.teardown()


def test_same_actor_nodes_stay_loop_local(ray_start_local):
    """Two chained methods on ONE actor: the edge between them needs no
    channel (loop-local), and execution is still correct."""
    import ray_tpu
    from ray_tpu.dag import InputNode

    (a,) = _make_adders(ray_tpu, 3)
    with InputNode() as inp:
        dag = a.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(0).get(timeout=10) == 6
        assert compiled.execute(10).get(timeout=10) == 16
        # exactly the driver-input and driver-output channels: the a->a edge
        # must not have allocated one
        assert len(compiled._channels) == 2
    finally:
        compiled.teardown()


def test_actor_revisit_graph(ray_start_local):
    """A → B → A: lazy per-node channel reads let a graph return to an
    actor it already visited (preprocess/postprocess on one actor, heavy
    stage on another) instead of deadlocking on the upfront read."""
    import ray_tpu
    from ray_tpu.dag import InputNode

    a, b = _make_adders(ray_tpu, 1, 10)
    with InputNode() as inp:
        dag = a.add2.bind(b.add.bind(a.add.bind(inp)), inp)
    compiled = dag.experimental_compile()
    try:
        for i in range(5):
            # a.add: i+1; b.add: i+11; a.add2(i+11, i): 2i+12
            assert compiled.execute(i).get(timeout=10) == 2 * i + 12
    finally:
        compiled.teardown()


def test_shm_channel_rejects_oversized_messages(tmp_path):
    """Messages over half the ring are rejected up front — at an unlucky
    offset a wrapped write of such a message could never find space."""
    from ray_tpu.cgraph import ShmChannel

    ch = ShmChannel(str(tmp_path / "c"), capacity=1 << 12, max_msgs=4,
                    create=True)
    with pytest.raises(ValueError, match="max message size"):
        ch.write(b"x" * 3000)
    ch.write(b"x" * 1500)
    assert ch.read(timeout=5) == b"x" * 1500
    ch.unlink()


def test_shm_channel_zero_copy_reads(tmp_path):
    """With zero_copy_reads on, large numpy payloads come back as READ-ONLY
    views over the ring's mmap (no copy out); a view is valid until the next
    read on the channel drains another message over it."""
    np = pytest.importorskip("numpy")
    from ray_tpu.cgraph import ShmChannel

    ch = ShmChannel(str(tmp_path / "c"), capacity=1 << 16, max_msgs=4,
                    create=True)
    ch.zero_copy_reads = True
    src = np.arange(2048, dtype=np.int64)
    ch.write({"arr": src})

    out = ch.read(timeout=5)["arr"]
    assert np.array_equal(out, src)
    assert not out.flags.writeable  # view over the ring, not a copy
    with pytest.raises(ValueError):
        out[0] = -1

    # lifetime rule: the slot is only released by the NEXT read, after
    # which the ring may recycle the bytes under the old view
    first = out.copy()
    for i in range(4):
        ch.write({"arr": src + i})
        assert np.array_equal(ch.read(timeout=5)["arr"], src + i)
    assert np.array_equal(first, src)  # the copy we took is untouched

    # copy-mode reads stay writable (default path unchanged)
    ch.zero_copy_reads = False
    ch.write({"arr": src})
    assert ch.read(timeout=5)["arr"].flags.writeable
    ch.unlink()


def test_error_propagates_and_pipeline_stays_aligned(ray_start_local):
    import ray_tpu
    from ray_tpu.dag import InputNode

    a, b = _make_adders(ray_tpu, 1, 10)
    with InputNode() as inp:
        dag = b.add.bind(a.boom.bind(inp))
    compiled = dag.experimental_compile()
    try:
        with pytest.raises(ValueError, match="boom:7"):
            compiled.execute(7).get(timeout=10)
        # the error drained through the graph as a message: the next
        # execute still lines up seq-for-seq
        with pytest.raises(ValueError, match="boom:8"):
            compiled.execute(8).get(timeout=10)
    finally:
        compiled.teardown()


def test_overlap_bounded_by_channel_capacity(ray_start_local):
    """With max_in_flight=2 and a slow sink, a burst beyond the channel
    capacity blocks at execute() (ChannelTimeoutError), and consuming
    results frees the slots."""
    import ray_tpu
    from ray_tpu.cgraph import ChannelTimeoutError
    from ray_tpu.dag import InputNode

    (s,) = _make_adders(ray_tpu, 0)
    with InputNode() as inp:
        dag = s.slow.bind(inp)
    compiled = dag.experimental_compile(max_in_flight=2)
    try:
        refs = []
        with pytest.raises(ChannelTimeoutError):
            for i in range(10):
                refs.append(compiled.execute(i, timeout=0.2))
        # capacity: 2 buffered on the input edge (+1 possibly mid-read in
        # the loop); far fewer than the 10 requested
        assert 2 <= len(refs) <= 4
        # drain results; the freed slots accept new work
        for i, r in enumerate(refs):
            assert r.get(timeout=10) == i
        assert compiled.execute(99, timeout=10).get(timeout=10) == 99
    finally:
        compiled.teardown()


def test_teardown_frees_actors_and_rejects_reuse(ray_start_local):
    import ray_tpu
    from ray_tpu.dag import InputNode

    a, b = _make_adders(ray_tpu, 1, 10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    assert compiled.execute(1).get(timeout=10) == 12
    compiled.teardown()
    compiled.teardown()  # idempotent
    with pytest.raises(RuntimeError, match="torn down"):
        compiled.execute(2)
    # the actors are released: ordinary method calls work again...
    assert ray_tpu.get(a.add.remote(5)) == 6
    # ...and a NEW graph over the same actors compiles
    with InputNode() as inp:
        dag2 = a.add.bind(inp)
    c2 = dag2.experimental_compile()
    try:
        assert c2.execute(0).get(timeout=10) == 1
    finally:
        c2.teardown()


def test_one_compiled_graph_per_actor(ray_start_local):
    import ray_tpu
    from ray_tpu.dag import InputNode

    (a,) = _make_adders(ray_tpu, 1)
    with InputNode() as inp:
        c1 = a.add.bind(inp).experimental_compile()
    try:
        with InputNode() as inp:
            with pytest.raises(ValueError, match="one compiled graph"):
                a.add.bind(inp).experimental_compile()
    finally:
        c1.teardown()


def test_actor_pipeline_microbatches(ray_start_local):
    from ray_tpu.parallel.pipeline import ActorPipeline

    pipe = ActorPipeline(
        [lambda x: x + 1, lambda x: x * 2, lambda x: x - 3],
        max_in_flight=4,
    )
    try:
        # many more microbatches than the window: exercises the sliding
        # submit/consume interleave
        outs = pipe.run(list(range(20)), timeout=15)
        assert outs == [(i + 1) * 2 - 3 for i in range(20)]
    finally:
        pipe.teardown()


@pytest.mark.slow
def test_cluster_mode_shm_channels_and_speedup(ray_start_regular):
    """End-to-end over real worker processes: the compiled path runs on
    shared-memory ring channels and beats interpreted dispatch."""
    import ray_tpu
    from ray_tpu.cgraph import ShmChannel
    from ray_tpu.dag import InputNode

    a, b = _make_adders(ray_tpu, 1, 10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))

    # interpreted timing first: compiling occupies the actors' executors
    assert ray_tpu.get(dag.execute(0)) == 11
    t0 = time.perf_counter()
    for i in range(10):
        assert ray_tpu.get(dag.execute(i)) == 11 + i
    dt_interp = (time.perf_counter() - t0) / 10

    compiled = dag.experimental_compile(max_in_flight=8)
    try:
        assert all(isinstance(ch, ShmChannel) for ch in compiled._channels)
        assert compiled.execute(0).get(timeout=30) == 11
        t0 = time.perf_counter()
        for i in range(30):
            assert compiled.execute(i).get(timeout=30) == 11 + i
        dt_comp = (time.perf_counter() - t0) / 30
        # the acceptance bar is "measurably lower"; in practice it is ~10x
        assert dt_comp < dt_interp, (dt_comp, dt_interp)
        # channel files are freed by teardown
        paths = [ch.path for ch in compiled._channels]
    finally:
        compiled.teardown()
    import os

    assert not any(os.path.exists(p) for p in paths)


@pytest.mark.slow
def test_serve_compiled_handle(ray_start_regular):
    """serve: the compiled fast path answers like the routed path and
    releases the replica on teardown."""
    import ray_tpu
    from ray_tpu.serve import api as serve

    @serve.deployment(name="doubler")
    class Doubler:
        def __call__(self, x):
            return 2 * x

    handle = serve.run(Doubler.bind())
    try:
        assert ray_tpu.get(handle.remote(21), timeout=30) == 42
        compiled = handle.compile(max_in_flight=4)
        try:
            refs = [compiled.remote(i, timeout=15) for i in range(6)]
            assert [r.get(timeout=15) for r in refs] == [2 * i for i in range(6)]
        finally:
            compiled.teardown()
        # routed path still works after teardown
        assert ray_tpu.get(handle.remote(5), timeout=30) == 10
    finally:
        serve.shutdown()
