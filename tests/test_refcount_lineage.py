"""Distributed refcounting + lineage reconstruction (VERDICT r2 item 6).

(a) an object is physically deleted from the store after its last ref drops;
(b) a lost object (raylet SIGKILL) is recomputed from its creating task.
Parity: reference_count.h:61, task_manager.h:164, object_recovery_manager.h:41.
"""

import gc
import os
import signal
import time

import numpy as np
import pytest


@pytest.fixture
def ray2():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def _shm_path(ray, ref):
    from ray_tpu.api import _global_worker

    core = _global_worker().backend.core
    from ray_tpu.core.object_store import shm_store

    return os.path.join(shm_store.session_dir(core.session), ref.id.hex())


def test_put_object_freed_after_last_ref(ray2):
    ray = ray2
    big = np.ones(1_000_000)  # 8 MB → shm, not inline
    ref = ray.put(big)
    path = _shm_path(ray, ref)
    assert ray.get(ref, timeout=30).sum() == 1_000_000
    assert os.path.exists(path)

    del ref
    gc.collect()
    deadline = time.time() + 20
    while os.path.exists(path) and time.time() < deadline:
        time.sleep(0.2)
    assert not os.path.exists(path), "shm file must be deleted after last ref"


def test_task_result_freed_after_last_ref(ray2):
    ray = ray2

    @ray.remote
    def make():
        return np.ones(1_000_000)

    ref = make.remote()
    assert ray.get(ref, timeout=60).sum() == 1_000_000
    path = _shm_path(ray, ref)
    assert os.path.exists(path)
    del ref
    gc.collect()
    deadline = time.time() + 20
    while os.path.exists(path) and time.time() < deadline:
        time.sleep(0.2)
    assert not os.path.exists(path)


def test_object_kept_alive_by_pending_task(ray2):
    ray = ray2
    data = ray.put(np.arange(1_000_000))
    path = _shm_path(ray, data)

    @ray.remote
    def slow_sum(arr):
        import time as t

        t.sleep(2)
        return int(arr.sum())

    result = slow_sum.remote(data)
    del data          # only the pending task pins it now
    gc.collect()
    time.sleep(0.5)
    assert os.path.exists(path), "arg must stay alive while the task runs"
    assert ray.get(result, timeout=60) == sum(range(1_000_000))


def test_lineage_reconstruction_after_store_loss(ray2):
    """Kill the object's shm copy out from under the owner; a get() must
    resubmit the creating task and return the value."""
    ray = ray2

    @ray.remote
    def produce():
        return np.full(1_000_000, 7.0)  # large → lives in shm

    ref = produce.remote()
    assert ray.get(ref, timeout=60)[0] == 7.0
    path = _shm_path(ray, ref)
    assert os.path.exists(path)

    # simulate losing the only copy (node death for that object): remove the
    # shm file AND the raylet's directory entry via the free path, keeping
    # the ref alive
    from ray_tpu.api import _global_worker

    core = _global_worker().backend.core
    os.unlink(path)

    got = ray.get(ref, timeout=120)
    assert got[0] == 7.0 and got.shape == (1_000_000,)


def test_lineage_reconstruction_after_raylet_sigkill():
    """Multi-node: object produced on node B; SIGKILL node B's raylet; the
    driver's get() reconstructs via lineage on a surviving node."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    cluster = Cluster(head_node_args={"num_cpus": 1})
    node_b = cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    try:
        @ray_tpu.remote(num_cpus=2, max_retries=2)
        def produce():
            return np.full(500_000, 3.0)

        # num_cpus=2 forces placement on node B
        ref = produce.remote()
        assert ray_tpu.get(ref, timeout=90)[0] == 3.0

        cluster.kill_node(node_b)  # SIGKILL the raylet holding the copy
        # the Cluster fixture shares one host (and thus one tmpfs session
        # dir); on a real deployment node B's shm dies with it — simulate
        # that by removing the file as well
        from ray_tpu.api import _global_worker
        from ray_tpu.core.object_store import shm_store

        core = _global_worker().backend.core
        path = os.path.join(shm_store.session_dir(core.session), ref.id.hex())
        if os.path.exists(path):
            os.unlink(path)
        time.sleep(1)
        cluster.add_node(num_cpus=2)      # capacity to re-run the task

        got = ray_tpu.get(ref, timeout=120)
        assert got[0] == 3.0 and got.shape == (500_000,)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_worker_owned_ref_in_result_not_freed(ray2):
    """A task that puts an object and returns the REF must not free it when
    its frame exits: the reply pre-registers the caller as a borrower
    (worker_main._grant_result_borrows). Regression: round-3 review."""
    ray = ray2

    @ray.remote
    def producer():
        inner = ray.put(np.ones(1_000_000))  # worker-owned, lives in shm
        return inner                          # nested ref crosses the wire

    outer = producer.remote()
    inner_ref = ray.get(outer, timeout=60)
    # the producing worker's frame exited long ago; give any stray free a
    # moment to land before reading
    time.sleep(1.0)
    assert ray.get(inner_ref, timeout=60).sum() == 1_000_000

    # and the borrow releases: dropping BOTH refs eventually deletes the shm
    from ray_tpu.api import _global_worker
    from ray_tpu.core.object_store import shm_store

    core = _global_worker().backend.core
    path = os.path.join(
        shm_store.session_dir(core.session), inner_ref.id.hex()
    )
    assert os.path.exists(path)
    del inner_ref, outer
    gc.collect()
    deadline = time.time() + 20
    while os.path.exists(path) and time.time() < deadline:
        time.sleep(0.2)
    assert not os.path.exists(path), "borrowed ref must free after release"


def test_reconstruction_attempts_are_bounded(ray2):
    """A lost object whose copies keep vanishing must not loop resubmission
    forever: after max(1, max_retries) lineage resubmits the get() surfaces
    ObjectLostError instead of spinning. Regression: round-3 review."""
    ray = ray2
    from ray_tpu.api import _global_worker

    core = _global_worker().backend.core

    @ray.remote(max_retries=1)
    def produce():
        return np.full(1_000_000, 5.0)

    ref = produce.remote()
    assert ray.get(ref, timeout=60)[0] == 5.0
    path = _shm_path(ray, ref)

    # sabotage: every reconstruction lands back in shm; delete the file each
    # time so the location read keeps failing
    import ray_tpu.exceptions as exc

    os.unlink(path)
    with pytest.raises((exc.ObjectLostError, exc.GetTimeoutError)):
        for _ in range(6):  # bounded: must raise well before 6 rounds
            os.path.exists(path) and os.unlink(path)
            ray.get(ref, timeout=20)
            os.unlink(path)


def test_arg_object_freed_after_consumer_and_spec_drop(ray2):
    """x = f(); y = g(x); del x keeps x alive (g's retained spec pins its
    lineage args); del y must then free BOTH. Also regression for the
    release-before-add borrow race: the consuming worker's release can beat
    the task reply's add_borrow across connections."""
    ray = ray2

    @ray.remote
    def f():
        return np.ones(500_000)

    @ray.remote
    def g(a):
        return float(a.sum())

    x = f.remote()
    y = g.remote(x)
    assert ray.get(y, timeout=60) == 500_000
    xpath = _shm_path(ray, x)
    del x
    gc.collect()
    time.sleep(1.5)
    assert os.path.exists(xpath), "lineage args stay pinned while y lives"
    del y
    gc.collect()
    deadline = time.time() + 20
    while os.path.exists(xpath) and time.time() < deadline:
        time.sleep(0.2)
    assert not os.path.exists(xpath), "x must free after its consumer's ref drops"
