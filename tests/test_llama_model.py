"""LLaMA-family model: shapes, learning, sharding, and HF numerics parity.

The HF-parity test is the anchor: our RoPE layout (rotate_half), GQA
repetition, RMSNorm, and SwiGLU must reproduce transformers'
LlamaForCausalLM logits on identical weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama


def test_forward_shapes_and_loss_decreases():
    cfg = llama.llama_tiny(dtype=jnp.float32)
    params = llama.init(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (2, 64)).astype(np.int32)
    tgt = np.roll(toks, -1, 1).copy()
    tgt[:, -1] = -1

    logits = llama.forward(params, toks, cfg)
    assert logits.shape == (2, 64, cfg.padded_vocab)

    import optax

    opt = optax.adam(1e-3)
    state = opt.init(params)
    loss_g = jax.jit(jax.value_and_grad(
        lambda p: llama.loss_fn(p, toks, tgt, cfg)
    ))
    l0, g = loss_g(params)
    for _ in range(20):
        l, g = loss_g(params)
        upd, state = opt.update(g, state)
        params = optax.apply_updates(params, upd)
    assert float(l) < float(l0) * 0.9


def test_gqa_equals_mha_when_kv_heads_match():
    """n_kv_head == n_head must reduce to standard attention."""
    cfg_g = llama.llama_tiny(dtype=jnp.float32, n_kv_head=4)
    params = llama.init(cfg_g, jax.random.PRNGKey(1))
    toks = np.arange(32, dtype=np.int32)[None, :] % cfg_g.vocab_size
    out = llama.forward(params, toks, cfg_g)
    assert np.all(np.isfinite(np.asarray(out, np.float32)))


def test_tp_fsdp_mesh_matches_single_device(cpu_mesh8):
    """Sharded forward over a tp2/fsdp2 mesh == single-device logits."""
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.parallel import sharding as sharding_lib

    cfg = llama.llama_tiny(dtype=jnp.float32)
    params = llama.init(cfg, jax.random.PRNGKey(2))
    toks = (np.arange(64, dtype=np.int32)[None, :] % cfg.vocab_size)
    ref = np.asarray(llama.forward(params, toks, cfg))

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(tp=2, fsdp=2), cpu_mesh8[:4])
    shardings = sharding_lib.tree_shardings(mesh, llama.logical_axes(cfg))
    sharded = jax.tree.map(jax.device_put, params, shardings)
    out = jax.jit(lambda p, t: llama.forward(p, t, cfg))(sharded, toks)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_hf_numerics_parity():
    """Logits match transformers' LlamaForCausalLM on identical weights."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = llama.llama_tiny(dtype=jnp.float32)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size,
        hidden_size=cfg.d_model,
        intermediate_size=cfg.d_ff,
        num_hidden_layers=cfg.n_layer,
        num_attention_heads=cfg.n_head,
        num_key_value_heads=cfg.n_kv_head,
        max_position_embeddings=cfg.seq_len,
        rms_norm_eps=cfg.rms_eps,
        rope_theta=cfg.rope_theta,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    params = llama.params_from_hf(hf, cfg)
    rng = np.random.default_rng(3)
    toks = rng.integers(0, cfg.vocab_size, (2, 48)).astype(np.int32)

    with torch.no_grad():
        ref = hf(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
    ours = np.asarray(
        llama.forward(params, toks, cfg)[:, :, : cfg.vocab_size], np.float32
    )
    np.testing.assert_allclose(ours, ref, rtol=2e-3, atol=2e-3)


def test_llama_train_step_on_mesh(cpu_mesh8):
    """Full sharded train step (train_step.make_llama_train_step) on a
    dp2/tp2 mesh: loss finite, decreases, params stay sharded."""
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.train.train_step import make_llama_train_step

    cfg = llama.llama_tiny(dtype=jnp.float32)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(dp=2, tp=2), cpu_mesh8[:4])
    bundle = make_llama_train_step(cfg, mesh=mesh, rng=jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, (4, 64)).astype(np.int32)
    tgt = np.roll(toks, -1, 1).copy()
    tgt[:, -1] = -1
    state = bundle.state
    losses = []
    for _ in range(8):
        state, m = bundle.step_fn(state, {"tokens": toks, "targets": tgt})
        losses.append(float(m["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    wq = state["params"]["blocks"]["wq"]
    assert "tp" in str(wq.sharding.spec), wq.sharding
