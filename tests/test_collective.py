"""Collective group API tests (parity: util/collective tests).

Host-plane collectives between actors: allreduce/broadcast/allgather/
barrier/send-recv through the object store + a named rendezvous actor.
"""

import numpy as np
import pytest


@pytest.fixture(scope="module")
def ray4():
    import ray_tpu

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()


def _worker_cls(ray):
    @ray.remote(num_cpus=1)
    class Rank:
        def __init__(self, rank, world, group):
            from ray_tpu.util import collective as col

            self.col = col
            self.group = col.init_collective_group(world, rank, group)
            self.rank = rank

        def allreduce(self, value):
            return self.group.allreduce(np.asarray(value, np.float32))

        def allgather(self, value):
            return self.group.allgather(np.asarray(value))

        def broadcast(self, value=None):
            return self.group.broadcast(value, src_rank=0)

        def reducescatter(self, value):
            return self.group.reducescatter(np.asarray(value, np.float32))

        def barrier_then(self, x):
            self.group.barrier()
            return x

        def send_to(self, dst, value):
            self.group.send(np.asarray(value), dst)
            return True

        def recv_from(self, src):
            return self.group.recv(src)

    return Rank


def test_allreduce_and_allgather(ray4):
    Rank = _worker_cls(ray4)
    ranks = [Rank.remote(i, 3, "g1") for i in range(3)]
    outs = ray4.get([r.allreduce.remote([1.0 * (i + 1)] * 4)
                     for i, r in enumerate(ranks)], timeout=60)
    for o in outs:
        np.testing.assert_allclose(o, [6.0] * 4)
    gathered = ray4.get([r.allgather.remote([i]) for i, r in enumerate(ranks)],
                        timeout=60)
    for g in gathered:
        assert [int(x[0]) for x in g] == [0, 1, 2]
    for r in ranks:
        ray4.kill(r)


def test_broadcast_and_barrier(ray4):
    Rank = _worker_cls(ray4)
    ranks = [Rank.remote(i, 2, "g2") for i in range(2)]
    outs = ray4.get(
        [ranks[0].broadcast.remote(np.arange(5)), ranks[1].broadcast.remote()],
        timeout=60,
    )
    np.testing.assert_array_equal(outs[0], np.arange(5))
    np.testing.assert_array_equal(outs[1], np.arange(5))
    assert ray4.get([r.barrier_then.remote(i) for i, r in enumerate(ranks)],
                    timeout=60) == [0, 1]
    for r in ranks:
        ray4.kill(r)


def test_reducescatter_shards(ray4):
    Rank = _worker_cls(ray4)
    ranks = [Rank.remote(i, 2, "g3") for i in range(2)]
    outs = ray4.get(
        [r.reducescatter.remote(np.ones(6)) for r in ranks], timeout=60
    )
    np.testing.assert_allclose(outs[0], [2.0, 2.0, 2.0])
    np.testing.assert_allclose(outs[1], [2.0, 2.0, 2.0])
    for r in ranks:
        ray4.kill(r)


def test_send_recv(ray4):
    Rank = _worker_cls(ray4)
    ranks = [Rank.remote(i, 2, "g4") for i in range(2)]
    send = ranks[0].send_to.remote(1, [7, 8, 9])
    got = ray4.get(ranks[1].recv_from.remote(0), timeout=60)
    assert ray4.get(send, timeout=60)
    np.testing.assert_array_equal(got, [7, 8, 9])
    for r in ranks:
        ray4.kill(r)
