"""Microbenchmark schema check (tier-1 CI node).

Runs ``python -m ray_tpu.microbenchmark --smoke --json`` — every section on
a tiny config — and asserts the emitted row-name set matches the module's
EXPECTED_ROWS registry exactly. No performance assertions (so it cannot
flake on a loaded box); what it catches is silent schema drift: a renamed,
dropped, or never-run row would otherwise corrupt MICROBENCH.json
comparisons across PRs without failing anything.
"""

import json
import os
import subprocess
import sys

from ray_tpu.microbenchmark import EXPECTED_ROWS


def test_smoke_emits_every_known_row(tmp_path):
    out = tmp_path / "smoke.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.microbenchmark", "--smoke",
         "--json", str(out)],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert proc.returncode == 0, (
        f"smoke run failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    payload = json.loads(out.read_text())
    rows = payload["microbenchmark"]
    names = [r["name"] for r in rows]
    assert sorted(names) == sorted(set(names)), "duplicate row names"
    missing = set(EXPECTED_ROWS) - set(names)
    unexpected = set(names) - set(EXPECTED_ROWS)
    assert not missing and not unexpected, (
        f"microbenchmark schema drift: missing={sorted(missing)} "
        f"unexpected={sorted(unexpected)} — update EXPECTED_ROWS and "
        "MICROBENCH.json together"
    )
    # every row carries at least one numeric field beyond its name
    for r in rows:
        assert any(
            isinstance(v, (int, float)) for k, v in r.items() if k != "name"
        ), f"row {r['name']!r} has no numeric payload: {r}"
