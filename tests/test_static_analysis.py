"""raylint + runtime-sanitizer tests.

Per-rule fixtures (one minimal positive and negative snippet per RT rule),
the suppression/baseline mechanics, the chaos-point/docs drift gates, the
runtime sanitizers (lock-order, io-loop watchdog, thread affinity), the
CLI, and — marked ``lint`` so the tier-1 gate is a single test node — the
whole-package run asserting zero unsuppressed findings.
"""

import json
import textwrap
import threading
import time

import pytest

from ray_tpu.analysis import lint_source


def _rules_of(result):
    return sorted({f.rule for f in result.unsuppressed})


def _lint(src: str, filename: str = "snippet.py"):
    return lint_source(textwrap.dedent(src), filename)


# ---------------------------------------------------------------- RT001
def test_rt001_blocking_in_async_def():
    res = _lint("""
        import time

        async def handler(self):
            time.sleep(1)
    """)
    assert "RT001" in _rules_of(res)


def test_rt001_io_run_reachable_from_async():
    # the PR-1 deadlock shape: an async handler calls a sync helper that
    # blocks on the io loop — caught through one-hop reachability
    res = _lint("""
        class W:
            async def handle_get(self):
                return self._fetch()

            def _fetch(self):
                return self.io.run(self._get_async())
    """)
    findings = [f for f in res.unsuppressed if f.rule == "RT001"]
    assert findings and "io.run" in findings[0].message


def test_rt001_negative_sync_and_awaited():
    res = _lint("""
        import asyncio
        import time

        def cli_loop():
            time.sleep(1)          # fine: not loop context

        async def poller(self):
            await asyncio.sleep(1)  # fine: async sleep
            return self.io.spawn(self._bg())  # fine: non-blocking spawn
    """)
    assert "RT001" not in _rules_of(res)


# ---------------------------------------------------------------- RT002
def test_rt002_lock_across_await():
    res = _lint("""
        async def update(self):
            with self._lock:
                await self._flush()
    """)
    assert "RT002" in _rules_of(res)


def test_rt002_negative():
    res = _lint("""
        async def update(self):
            with self._lock:
                self.n += 1            # released before the await
            await self._flush()
            async with self._alock:    # asyncio lock: fine
                await self._flush()

        def sync_update(self):
            with self._lock:
                self.n += 1
    """)
    assert "RT002" not in _rules_of(res)


# ---------------------------------------------------------------- RT003
def test_rt003_bare_ensure_future():
    res = _lint("""
        import asyncio

        def kick(self):
            asyncio.ensure_future(self._dispatch())
    """)
    assert "RT003" in _rules_of(res)


def test_rt003_lambda_callback():
    res = _lint("""
        import asyncio

        def retry_later(self, loop, info):
            loop.call_later(1.0, lambda: asyncio.ensure_future(self._go(info)))
    """)
    assert "RT003" in _rules_of(res)


def test_rt003_negative_held():
    res = _lint("""
        import asyncio

        def kick(self):
            t = asyncio.ensure_future(self._dispatch())
            self._held.add(t)
            t.add_done_callback(self._held.discard)
            self._hold(asyncio.create_task(self._other()))
    """)
    assert "RT003" not in _rules_of(res)


# ---------------------------------------------------------------- RT004
def test_rt004_del_blocking_kill():
    # deliberately reintroduce the PR-1 pattern: __del__ -> blocking
    # kill through the backend plane — raylint must make lint exit dirty
    res = _lint("""
        class ActorHandle:
            def __del__(self):
                _global_worker().backend.kill_actor(self._actor_id, True)
    """)
    assert "RT004" in _rules_of(res)


def test_rt004_del_io_run_and_teardown():
    res = _lint("""
        class G:
            def __del__(self):
                self.io.run(self._close_async())

        class D:
            def __del__(self):
                self.teardown(timeout=1.0)
    """)
    assert len([f for f in res.unsuppressed if f.rule == "RT004"]) == 2


def test_rt004_negative_flag_flip():
    res = _lint("""
        class Ref:
            def __del__(self):
                self._closed = True
                cb = self._on_close
                if cb is not None:
                    cb(self)
    """)
    assert "RT004" not in _rules_of(res)


# ---------------------------------------------------------------- RT005
def test_rt005_unregistered_point():
    res = _lint("""
        from ray_tpu.testing import chaos

        def send(self):
            act = chaos.fire("rpc.sned", key="x")
    """)
    findings = [f for f in res.unsuppressed if f.rule == "RT005"]
    assert findings and "rpc.sned" in findings[0].message


def test_rt005_non_literal_point():
    res = _lint("""
        from ray_tpu.testing import chaos

        def send(self, point):
            chaos.fire(point, key="x")
    """)
    assert "RT005" in _rules_of(res)


def test_rt005_negative_registered():
    res = _lint("""
        from ray_tpu.testing import chaos

        def send(self):
            act = chaos.fire("rpc.send", key="x")
    """)
    assert "RT005" not in _rules_of(res)


def test_chaos_plan_rejects_unknown_point_at_runtime():
    from ray_tpu.testing import chaos

    with pytest.raises(ValueError, match="unknown chaos point"):
        chaos.plan(1)._rule("not.a.point", "kill")
    # builders still work for every registered point
    p = (chaos.plan(2).kill_worker().kill_actor("A.b").slow_replica("d")
         .kill_cgraph_actor().kill_stream_producer().sever_channel()
         .drop_rpc("kv_put").delay_rpc("kv_get").sever_rpc("put")
         .restart_gcs())
    assert len(p.rules) == 10


# ---------------------------------------------------------------- RT006
def test_rt006_unknown_config_knob():
    res = _lint("""
        from ray_tpu.core.config import _config

        def f():
            return _config.worker_lease_timeout_msec
    """)
    assert "RT006" in _rules_of(res)


def test_rt006_unknown_metric_and_env():
    res = _lint("""
        import os
        from ray_tpu.util.metrics import Counter

        c = Counter("serve_requsets_total")
        tok = os.environ.get("RAY_TPU_BOGUS_KNOB")
    """)
    assert len([f for f in res.unsuppressed if f.rule == "RT006"]) == 2


def test_rt006_reader_drift():
    res = _lint("""
        def qps(samples, counter_rate):
            return counter_rate(samples, "serve_requests_totall")
    """)
    assert "RT006" in _rules_of(res)


def test_rt006_negative():
    res = _lint("""
        import os
        from ray_tpu.core.config import _config
        from ray_tpu.util.metrics import Counter

        c = Counter("serve_requests_total")
        t = _config.task_max_retries
        tok = os.environ.get("RAY_TPU_TOKEN")
        knob = os.environ.get("RAY_TPU_SANITIZE_LOOP_STALL_S")
    """)
    assert "RT006" not in _rules_of(res)


# ---------------------------------------------------------------- RT007
def test_rt007_mixed_clocks():
    res = _lint("""
        import time

        def elapsed():
            return time.time() - time.monotonic()
    """)
    assert "RT007" in _rules_of(res)


def test_rt007_monotonic_vs_spec_deadline():
    res = _lint("""
        import time

        def expired(spec):
            return time.monotonic() > spec.deadline
    """)
    findings = [f for f in res.unsuppressed if f.rule == "RT007"]
    assert findings and "wall-clock" in findings[0].message


def test_rt007_negative():
    res = _lint("""
        import time

        def expired(spec):
            return time.time() > spec.deadline      # correct clock domain

        def local_wait(deadline):
            return time.monotonic() > deadline      # local monotonic: fine
    """)
    assert "RT007" not in _rules_of(res)


# ------------------------------------------------- suppressions + baseline
def test_suppression_with_reason():
    res = _lint("""
        import time

        async def handler(self):
            # raylint: disable=RT001(intentional fixture)
            time.sleep(1)
    """)
    assert res.clean
    assert any(f.rule == "RT001" and f.suppressed for f in res.findings)


def test_suppression_without_reason_is_rt000():
    res = _lint("""
        import time

        async def handler(self):
            time.sleep(1)  # raylint: disable=RT001
    """)
    assert not res.clean
    assert "RT000" in _rules_of(res)


def test_unused_suppression_is_rt000():
    res = _lint("""
        def fine():
            # raylint: disable=RT002(nothing here needs this)
            return 1
    """)
    assert "RT000" in _rules_of(res)


def test_baseline_grandfathers_non_core(tmp_path):
    from ray_tpu.analysis.linter import ModuleInfo, lint_modules

    src = textwrap.dedent("""
        import time

        async def handler(self):
            time.sleep(1)
    """)
    mod = ModuleInfo("x.py", "ray_tpu/rllib/x.py", src)
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([{
        "rule": "RT001", "path": "ray_tpu/rllib/x.py",
        "line_text": "time.sleep(1)",
        "reason": "legacy sleep in rollout loop; tracked in ROADMAP",
    }]))
    res = lint_modules([mod], baseline_path=str(bl))
    assert res.clean
    assert any(f.baselined for f in res.findings)


def test_baseline_rejected_for_core_planes(tmp_path):
    from ray_tpu.analysis.linter import ModuleInfo, lint_modules

    mod = ModuleInfo("x.py", "ray_tpu/rllib/x.py", "x = 1\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([{
        "rule": "RT001", "path": "ray_tpu/core/rpc.py",
        "line_text": "time.sleep(1)", "reason": "nope",
    }]))
    res = lint_modules([mod], baseline_path=str(bl))
    assert any("core-plane" in e for e in res.errors)


def test_baseline_stale_entry_is_error(tmp_path):
    from ray_tpu.analysis.linter import ModuleInfo, lint_modules

    mod = ModuleInfo("x.py", "ray_tpu/rllib/x.py", "x = 1\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([{
        "rule": "RT003", "path": "ray_tpu/rllib/x.py",
        "line_text": "asyncio.ensure_future(f())", "reason": "gone",
    }]))
    res = lint_modules([mod], baseline_path=str(bl))
    assert any("stale" in e for e in res.errors)


# ------------------------------------------------------------- docs drift
def test_readme_chaos_table_in_sync():
    from ray_tpu.analysis import docs
    from ray_tpu.testing.chaos import REGISTERED_POINTS

    md = docs.render_chaos_points_md()
    for point in REGISTERED_POINTS:
        assert f"`{point}`" in md
    assert docs.readme_in_sync(), (
        "README chaos-point table drifted from chaos.REGISTERED_POINTS — "
        "run `python -m ray_tpu.scripts lint --update-docs`"
    )


# -------------------------------------------------------------- sanitizers
def test_lock_order_cycle_detected_single_threaded():
    from ray_tpu.analysis import sanitizers as san

    san.enable(True)
    with san.scoped(drop_prefixes=("t.",)):
        # deltas vs the pre-scope globals: an unrelated violation recorded
        # earlier in the suite (a watchdog loop-stall on a loaded box) must
        # not fail this test's own-lock assertions
        base = san.violation_counts()
        a = san.SanitizedLock("t.A")
        b = san.SanitizedLock("t.B")
        with a:
            with b:
                pass
        assert san.violation_counts() == base
        with b:
            with a:        # inversion: closes the A->B cycle
                pass
        counts = san.violation_counts()
        assert counts.get("lock_order", 0) == base.get("lock_order", 0) + 1
        v = san.violations("lock_order")[-1]
        assert len([s for s in v["stacks"] if s]) == 2  # both stacks
        # same cycle reported once
        with b:
            with a:
                pass
        assert san.violation_counts().get("lock_order", 0) == \
            base.get("lock_order", 0) + 1


def test_lock_order_no_false_positive_consistent_order():
    from ray_tpu.analysis import sanitizers as san

    san.enable(True)
    with san.scoped(drop_prefixes=("c.",)):
        base = san.violation_counts()
        a, b = san.SanitizedLock("c.A"), san.SanitizedLock("c.B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert san.violation_counts() == base


def test_sanitized_condition_wait_notify():
    from ray_tpu.analysis import sanitizers as san

    san.enable(True)
    with san.scoped(drop_prefixes=("t.",)):
        base = san.violation_counts()
        cond = san.make_condition("t.cond")
        hits = []

        def waiter():
            with cond:
                while not hits:
                    cond.wait(timeout=5)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.1)
        with cond:
            hits.append(1)
            cond.notify_all()
        t.join(timeout=5)
        assert not t.is_alive()
        assert san.violation_counts() == base


def test_loop_watchdog_catches_blocked_loop():
    from ray_tpu.analysis import sanitizers as san
    from ray_tpu.core.config import _config
    from ray_tpu.core.rpc import EventLoopThread

    san.enable(True)
    old_stall = _config.sanitize_loop_stall_s
    old_ping = _config.sanitize_loop_ping_interval_s
    _config.sanitize_loop_stall_s = 0.3
    _config.sanitize_loop_ping_interval_s = 0.1
    elt = None
    try:
        with san.scoped(drop_prefixes=("watchdog-test",)):
            base = san.violation_counts().get("loop_stall", 0)
            elt = EventLoopThread(name="watchdog-test-io")

            async def block():
                time.sleep(1.2)  # raylint: disable=RT001(fixture: deliberately blocks the loop to trip the watchdog)

            elt.spawn(block())
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if san.violation_counts().get("loop_stall", 0) > base:
                    break
                time.sleep(0.05)
            assert san.violation_counts().get("loop_stall", 0) > base, \
                "watchdog missed a 1.2s loop block"
            v = san.violations("loop_stall")[-1]
            assert "heartbeat" in v["detail"]
    finally:
        _config.sanitize_loop_stall_s = old_stall
        _config.sanitize_loop_ping_interval_s = old_ping
        if elt is not None:
            elt.stop()


def test_thread_affinity_assert():
    from ray_tpu.analysis import sanitizers as san

    san.enable(True)
    with san.scoped(drop_prefixes=("t.",)):
        base = san.violation_counts()
        san.assert_thread_affinity("t.struct", threading.get_ident())
        assert san.violation_counts() == base
        san.assert_thread_affinity("t.struct", threading.get_ident() + 1)
        assert san.violation_counts().get("affinity", 0) == \
            base.get("affinity", 0) + 1


def test_sanitizer_counts_in_summarize_metrics(ray_start_local):
    from ray_tpu.analysis import sanitizers as san
    from ray_tpu.util import state

    san.enable(True)
    with san.scoped(drop_prefixes=("test",)):
        san.record_violation("loop_stall", "test", "fixture violation")
        m = state.summarize_metrics()
        assert m["sanitizer_violations"].get("loop_stall", 0) >= 1


# --------------------------------------------------------------------- CLI
def test_cli_lint_json_and_exit_codes(tmp_path):
    from ray_tpu.scripts import main

    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import asyncio

        def kick(self):
            asyncio.ensure_future(self._dispatch())
    """))
    assert main(["lint", str(bad)]) == 1
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert main(["lint", str(good)]) == 0
    # --json emits machine-readable findings
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(["lint", "--json", str(bad)])
    assert rc == 1
    data = json.loads(buf.getvalue())
    assert data["findings"] and data["findings"][0]["rule"] == "RT003"
    assert data["clean"] is False


# ------------------------------------------------------------- tier-1 gate
@pytest.mark.lint
def test_package_lint_clean():
    """THE gate: zero unsuppressed raylint findings over the whole
    package, no framework errors, no stale baseline entries."""
    from ray_tpu.analysis import lint_package

    res = lint_package()
    msg = "\n".join(str(f) for f in res.unsuppressed)
    assert res.unsuppressed == [], f"raylint findings:\n{msg}"
    assert res.errors == [], f"raylint errors:\n" + "\n".join(res.errors)
    assert res.files > 100  # sanity: the walk really covered the package
