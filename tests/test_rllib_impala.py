"""IMPALA stack: v-trace numerics, the built-in Pong env, async learning.

Parity targets: rllib/algorithms/impala/ (BASELINE config 4). The learning
test uses CartPole (fast, deterministic threshold); Pong is exercised for
env correctness + an async smoke (full Pong training is a benchmark run,
not a unit test).
"""

import numpy as np
import pytest


# --------------------------------------------------------------------- vtrace
def _vtrace_numpy(behavior_logp, target_logp, rewards, values, bootstrap,
                  discounts, clip_rho=1.0, clip_c=1.0):
    """Straightforward O(T) reference implementation (paper, eq. 1)."""
    T, N = rewards.shape
    rhos = np.exp(target_logp - behavior_logp)
    crhos = np.minimum(rhos, clip_rho)
    cs = np.minimum(rhos, clip_c)
    values_t1 = np.concatenate([values[1:], bootstrap[None]], 0)
    deltas = crhos * (rewards + discounts * values_t1 - values)
    vs_minus_v = np.zeros((T + 1, N))
    for t in reversed(range(T)):
        vs_minus_v[t] = deltas[t] + discounts[t] * cs[t] * vs_minus_v[t + 1]
    vs = values + vs_minus_v[:-1]
    vs_t1 = np.concatenate([vs[1:], bootstrap[None]], 0)
    pg_adv = crhos * (rewards + discounts * vs_t1 - values)
    return vs, pg_adv


def test_vtrace_matches_numpy_reference():
    from ray_tpu.rllib.vtrace import vtrace_from_logps

    rng = np.random.default_rng(0)
    T, N = 17, 5
    behavior = rng.normal(-1.2, 0.4, (T, N)).astype(np.float32)
    target = behavior + rng.normal(0, 0.3, (T, N)).astype(np.float32)
    rewards = rng.normal(0, 1, (T, N)).astype(np.float32)
    values = rng.normal(0, 1, (T, N)).astype(np.float32)
    bootstrap = rng.normal(0, 1, N).astype(np.float32)
    done = rng.random((T, N)) < 0.1
    discounts = (0.99 * (1 - done)).astype(np.float32)

    out = vtrace_from_logps(behavior, target, rewards, values, bootstrap,
                            discounts)
    ref_vs, ref_pg = _vtrace_numpy(behavior, target, rewards, values,
                                   bootstrap, discounts)
    np.testing.assert_allclose(np.asarray(out.vs), ref_vs, rtol=2e-5,
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(out.pg_advantages), ref_pg,
                               rtol=2e-5, atol=2e-5)


def test_vtrace_on_policy_reduces_to_discounted_td():
    """With rho == 1 (on-policy), vs must equal the n-step TD(λ=1) targets."""
    from ray_tpu.rllib.vtrace import vtrace_from_logps

    T, N = 6, 2
    logp = np.full((T, N), -0.5, np.float32)
    rewards = np.ones((T, N), np.float32)
    values = np.zeros((T, N), np.float32)
    bootstrap = np.zeros(N, np.float32)
    discounts = np.full((T, N), 0.9, np.float32)
    out = vtrace_from_logps(logp, logp, rewards, values, bootstrap, discounts)
    # vs[t] = sum_{k>=t} 0.9^{k-t} * 1
    expect = np.array(
        [sum(0.9 ** (k - t) for k in range(t, T)) for t in range(T)],
        np.float32,
    )[:, None].repeat(N, 1)
    np.testing.assert_allclose(np.asarray(out.vs), expect, rtol=1e-5,
                               atol=1e-5)


# ----------------------------------------------------------------------- pong
def test_pong_env_basics():
    from ray_tpu.rllib.env.pong import PongVectorEnv

    env = PongVectorEnv(4)
    obs = env.reset(seed=3)
    assert obs.shape == (4, 8) and obs.dtype == np.float32
    total_r = np.zeros(4)
    rng = np.random.default_rng(0)
    for _ in range(2000):
        obs, r, term, trunc, = env.step(rng.integers(0, 3, 4))
        assert obs.shape == (4, 8)
        assert np.isfinite(obs).all()
        assert ((r == 0) | (r == 1) | (r == -1)).all()
        total_r += r
    # points get scored within 2000 steps of random play
    assert (total_r != 0).any()


def test_pong_tracking_opponent_beats_noop():
    """A NOOP agent must lose points (opponent tracks and returns serves)."""
    from ray_tpu.rllib.env.pong import PongVectorEnv

    env = PongVectorEnv(2)
    env.reset(seed=5)
    total = np.zeros(2)
    for _ in range(4000):
        _, r, _, _ = env.step(np.zeros(2, np.int64))
        total += r
    assert (total < 0).all(), f"noop agent should lose, got {total}"


# --------------------------------------------------------------------- learn
def test_impala_learns_cartpole_sync():
    """Single-process IMPALA (inline sampling) must learn CartPole quickly."""
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig

    algo = (
        IMPALAConfig()
        .environment("CartPole-v1", num_envs_per_worker=16)
        .rollouts(num_rollout_workers=0, rollout_fragment_length=64)
        .training(lr=5e-4, entropy_coeff=0.005, updates_per_iteration=8)
        .debugging(seed=0)
        .build()
    )
    best = -np.inf
    for it in range(40):
        m = algo.train()
        r = m.get("episode_reward_mean", float("nan"))
        if np.isfinite(r):
            best = max(best, r)
        if best >= 150:
            break
    assert best >= 150, f"IMPALA failed to learn CartPole: best={best}"



def test_impala_async_workers_smoke():
    """2 async rollout actors + driver learner: batches stream, weights move,
    env_steps/sec is reported. Short run — correctness, not convergence."""
    import ray_tpu
    from ray_tpu.rllib.algorithms.impala import IMPALAConfig

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=3, num_tpus=0)
    try:
        algo = (
            IMPALAConfig()
            .environment("Pong-v0", num_envs_per_worker=4)
            .rollouts(num_rollout_workers=2, rollout_fragment_length=32)
            .training(updates_per_iteration=4)
            .debugging(seed=1)
            .build()
        )
        m1 = algo.train()
        m2 = algo.train()
        assert m2["timesteps_this_iter"] > 0
        assert m2["env_steps_per_sec"] > 0
        assert "total_loss" in m2
    finally:
        ray_tpu.shutdown()
