"""Experiment-level resume: kill a live Tune run, Tuner.restore() finishes it.

Parity: tune/execution/experiment_state.py + Tuner.restore (tuner.py:53) —
the crashed-experiment recovery path (VERDICT r3 gap #6: a crashed PBT run
restarted from zero).
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from ray_tpu.tune.experiment_state import STATE_FILE


DRIVER = textwrap.dedent("""
    import sys, time
    sys.path.insert(0, {repo!r})
    import ray_tpu
    from ray_tpu import tune
    from ray_tpu.tune.schedulers import PopulationBasedTraining
    from ray_tpu.tune.trainable import Trainable
    from ray_tpu.train.config import RunConfig

    class Slow(Trainable):
        def setup(self, config):
            self.total = 0.0
        def step(self):
            time.sleep(0.35)
            self.total += 1.0
            return {{"score": self.total + self.config.get("lr", 0)}}
        def save_checkpoint(self, d):
            return {{"total": self.total}}
        def load_checkpoint(self, ck):
            self.total = ck["total"]

    ray_tpu.init(num_cpus=4, num_tpus=0)
    rc = RunConfig(name="exp", storage_path={storage!r})
    rc.stop = {{"training_iteration": 12}}
    tuner = tune.Tuner(
        Slow,
        param_space={{"lr": tune.grid_search([0.1, 0.2, 0.3, 0.4])}},
        tune_config=tune.TuneConfig(
            metric="score", mode="max", max_concurrent_trials=4,
            scheduler=PopulationBasedTraining(
                perturbation_interval=3,
                hyperparam_mutations={{"lr": [0.1, 0.2, 0.3, 0.4]}},
            ),
        ),
        run_config=rc,
    )
    tuner.fit()
    print("DRIVER_DONE")
""")


def _state(exp_dir):
    with open(os.path.join(exp_dir, STATE_FILE)) as f:
        return json.load(f)


def test_kill_and_restore_pbt_run(tmp_path):
    import ray_tpu

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    storage = str(tmp_path)
    exp_dir = os.path.join(storage, "exp")
    script = DRIVER.format(repo=repo, storage=storage)

    # phase 1: run in a subprocess, SIGKILL the whole session mid-flight
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if os.path.exists(os.path.join(exp_dir, STATE_FILE)):
                st = _state(exp_dir)
                progressed = [
                    t for t in st["trials"] if len(t.get("results") or []) >= 2
                ]
                if len(progressed) >= 2:
                    break
            if proc.poll() is not None:
                out = proc.stdout.read().decode()
                raise AssertionError(f"driver exited early:\n{out}")
            time.sleep(0.25)
        else:
            raise AssertionError("experiment never progressed")
    finally:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait(timeout=30)

    st = _state(exp_dir)
    pre_iters = {
        t["trial_id"]: len(t.get("results") or []) for t in st["trials"]
    }
    assert any(v >= 2 for v in pre_iters.values())
    assert not all(
        t["status"] in ("TERMINATED", "ERROR") for t in st["trials"]
    ), "kill landed after completion; nothing to resume"

    # phase 2: restore in this process and run to completion
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    try:
        from ray_tpu import tune

        tuner = tune.Tuner.restore(exp_dir)
        grid = tuner.fit()
        assert len(grid) == 4
        for t in grid:
            iters = [r["training_iteration"] for r in t.results]
            # history intact: pre-kill iterations retained, post-restore
            # iterations CONTINUE (a from-scratch restart would replay
            # iteration 1.. again → duplicates)
            assert iters == sorted(set(iters)), iters
            assert max(iters) >= 12, iters
            # the checkpointed counter survived: total tracks iteration
            final = t.results[-1]
            assert final["score"] == pytest.approx(
                max(iters) + t.config.get("lr", 0), abs=1e-6
            )
        best = grid.get_best_result()
        assert best.metric("score") >= 12
    finally:
        ray_tpu.shutdown()
