"""Object lifecycle governance (ISSUE 17): one state machine for primary
pinning, proactive spill, dead-node restore, and last-resort lineage
recovery.

Unit level drives ObjectDirectory/ObjectRecord directly; cluster level uses
SPLIT shm sessions (same pattern as test_object_plane.py) so transfers,
spills and node deaths are genuine — a killed raylet's shm really is
unreachable, only its spill files survive on the shared host disk.
"""

import asyncio
import os
import shutil
import time
import uuid

import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.config import _config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store.lifecycle import (
    LEGAL_TRANSITIONS,
    IllegalTransitionError,
    ObjectRecord,
    ObjectState,
    spill_crc,
)
from ray_tpu.core.object_store.shm_store import ObjectDirectory, ShmClient

_CHUNK = 256 * 1024
_ENV = {
    "RAY_TPU_PULL_CHUNK_BYTES": str(_CHUNK),
}
# aggressive-spill daemon env: spill EVERY cold primary on a fast sweep
_SPILL_ENV = {
    **_ENV,
    "RAY_TPU_OBJECT_SPILL_THRESHOLD_FRAC": "0.0",
    "RAY_TPU_OBJECT_SPILL_INTERVAL_S": "0.1",
}


def _start_split_cluster(specs, extra_env=None):
    """GCS + one raylet per spec, each raylet in its OWN shm session."""
    from ray_tpu.core.cluster_backend import (
        ProcessGroup,
        _session_tmp_dir,
        start_gcs,
        start_raylet,
    )

    ray_tpu.shutdown()
    env = dict(_ENV)
    env.update(extra_env or {})
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    sessions = []
    procs = ProcessGroup(_session_tmp_dir(f"s{uuid.uuid4().hex[:10]}"))
    gcs = start_gcs(procs)
    for spec in specs:
        session = f"s{uuid.uuid4().hex[:10]}"
        sessions.append(session)
        start_raylet(
            procs, gcs, session, spec["name"],
            num_cpus=spec.get("num_cpus", 1), num_tpus=0,
            resources=spec.get("resources"),
            object_store_memory_mb=spec.get("store_mb"),
        )
    return procs, gcs, sessions, saved


def _teardown_split_cluster(procs, sessions, saved):
    from ray_tpu.core.object_store.shm_store import session_dir

    ray_tpu.shutdown()
    procs.shutdown()
    for s in sessions:
        shutil.rmtree(session_dir(s), ignore_errors=True)
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _core():
    from ray_tpu.api import _global_worker

    return _global_worker().backend.core


def _raylet_addr_of(core, node_id):
    async def view():
        return await core.gcs.call("get_resource_view", timeout=30)

    nodes = core.io.run(view(), timeout=60)
    return nodes[node_id]["address"]


def _store_stats(core, addr=None):
    async def stats():
        if addr is None:
            return await core.raylet.call("object_store_stats", timeout=30)
        conn = await core._conn_to(addr, kind="raylet")
        return await conn.call("object_store_stats", timeout=30)

    return core.io.run(stats(), timeout=60)


def _locations(core, oid_hex):
    async def locs():
        return await core.gcs.call(
            "object_locations", oid_hex=oid_hex, timeout=30
        )

    return core.io.run(locs(), timeout=60)


def _mkdir_directory(capacity_bytes=4 * 1024 * 1024):
    session = f"t{uuid.uuid4().hex[:8]}"
    client = ShmClient(session)
    spill = os.path.join("/tmp", f"spill_{session}")
    return client, ObjectDirectory(
        client, capacity_bytes=capacity_bytes, spill_dir=spill
    )


# ------------------------------------------------------------- unit level
def test_transition_matrix_is_exhaustive():
    """Every one of the 25 (src, dst) state pairs either walks cleanly or
    raises the typed IllegalTransitionError — exactly per the transition
    table. No transition silently no-ops into a wrong state."""
    for src in ObjectState:
        for dst in ObjectState:
            rec = ObjectRecord(nbytes=1, created_at=0.0, last_access=0.0,
                               state=src)
            if (src, dst) in LEGAL_TRANSITIONS:
                rec.transition(dst, "aa")
                assert rec.state is dst
            else:
                with pytest.raises(IllegalTransitionError) as ei:
                    rec.transition(dst, "aa")
                assert src.value in str(ei.value)
                assert dst.value in str(ei.value)
                assert rec.state is src  # state unchanged on refusal
    # the table itself stays minimal: FREED is terminal, nothing enters
    # RESTORING except from SPILLED
    assert not any(src is ObjectState.FREED for src, _ in LEGAL_TRANSITIONS)
    assert all(src is ObjectState.SPILLED
               for src, dst in LEGAL_TRANSITIONS
               if dst is ObjectState.RESTORING)


def test_pin_lease_renews_and_expires():
    rec = ObjectRecord(nbytes=8, created_at=0.0, last_access=0.0)
    assert not rec.pinned()
    rec.pin(ttl_s=30.0)
    assert rec.pinned()
    # renewal extends, never shortens
    long_deadline = rec.pin_expires
    rec.pin(ttl_s=0.001)
    assert rec.pin_expires == long_deadline
    # an expired lease ages out without any unpin call (owner crashed)
    rec2 = ObjectRecord(nbytes=8, created_at=0.0, last_access=0.0)
    rec2.pin(ttl_s=0.01)
    time.sleep(0.05)
    assert not rec2.pinned()
    rec.unpin()
    assert not rec.pinned()


def test_pinned_primary_never_dropped_refusal_is_typed():
    """Under pressure with spill failing (chaos object.spill), a pinned
    primary must survive in memory and the capacity request must refuse
    (False -> typed ObjectStoreFullError upstream) — never a silent
    drop."""
    from ray_tpu.testing import chaos

    client, d = _mkdir_directory(capacity_bytes=1024 * 1024)
    try:
        oid = ObjectID.from_random()
        data = os.urandom(700_000)
        client.put_bytes(oid, data)
        d.add(oid, len(data), role="primary")
        assert d.pin(oid, ttl_s=60.0)
        with chaos.plan(3).fail_spill(repeat=True):
            refused = d.ensure_capacity(600_000)
        assert refused is False
        rec = d.entries[oid]
        assert rec.state is ObjectState.PRIMARY and rec.in_memory
        assert client.contains(oid)
        # with spill working again the same request succeeds: the pinned
        # primary moves to disk (never destroyed) and frees its shm bytes
        assert d.ensure_capacity(600_000)
        rec = d.entries[oid]
        assert rec.state is ObjectState.SPILLED
        assert rec.spill_path and os.path.exists(rec.spill_path)
        assert d.restore(oid)  # and the live ref can still read it back
        buf = client.get(oid)
        try:
            assert bytes(buf.buffer) == data
        finally:
            buf.close()
    finally:
        d.destroy()
        client.destroy()


def test_restore_refuses_torn_spill_file():
    """A corrupted spill file fails the crc check: restore() returns False
    (typed upstream, the pull ladder takes over) and NEVER returns wrong
    bytes; the record drops back to SPILLED, not a half-restored state."""
    client, d = _mkdir_directory()
    try:
        oid = ObjectID.from_random()
        data = os.urandom(64 * 1024)
        client.put_bytes(oid, data)
        d.add(oid, len(data), role="primary")
        assert d.spill_cold(0) == 1
        rec = d.entries[oid]
        with open(rec.spill_path, "r+b") as f:  # torn mid-write
            f.seek(1000)
            f.write(b"\x00" * 512)
        assert spill_crc(open(rec.spill_path, "rb").read()) != rec.spill_crc
        assert d.restore(oid) is False
        assert d.entries[oid].state is ObjectState.SPILLED
        assert not client.contains(oid)
    finally:
        d.destroy()
        client.destroy()


def test_chaos_fail_restore_is_typed_not_corrupt():
    from ray_tpu.testing import chaos

    client, d = _mkdir_directory()
    try:
        oid = ObjectID.from_random()
        client.put_bytes(oid, os.urandom(32 * 1024))
        d.add(oid, 32 * 1024, role="primary")
        assert d.spill_cold(0) == 1
        with chaos.plan(9).fail_restore() as plan:
            assert d.restore(oid) is False
            events = [e for e in plan.events()
                      if e["point"] == "object.restore"]
        assert events and events[0]["action"] == "fail"
        assert d.entries[oid].state is ObjectState.SPILLED
        assert d.restore(oid)  # next attempt (no injection) succeeds
    finally:
        d.destroy()
        client.destroy()


def test_delete_removes_spill_file_and_notifies():
    """Owner free of a spill-backed object: record, shm copy and spill
    file all go, and the eviction listener fires so the raylet
    deregisters the (spill-registered) GCS location."""
    client, d = _mkdir_directory()
    notified = []
    d.evict_listener = notified.extend
    try:
        oid = ObjectID.from_random()
        client.put_bytes(oid, os.urandom(16 * 1024))
        d.add(oid, 16 * 1024, role="primary")
        assert d.spill_cold(0) == 1
        spill_path = d.entries[oid].spill_path
        assert os.path.exists(spill_path)
        d.delete(oid)
        assert oid not in d.entries
        assert not os.path.exists(spill_path)
        assert notified == [oid]
    finally:
        d.destroy()
        client.destroy()


# ------------------------------------------------------- pull fairness
def test_pull_fairness_prevents_cross_job_starvation():
    """Per-job budget fairness: job A floods the pull queue; job B's first
    pull must admit as soon as a slot frees — ahead of A's parked
    backlog — instead of waiting out A's whole FIFO queue."""
    from ray_tpu.core.object_store.pull_manager import PullManager

    saved = _config.pull_max_inflight_bytes
    _config.pull_max_inflight_bytes = 2 * 1024 * 1024
    session = f"t{uuid.uuid4().hex[:8]}"
    client = ShmClient(session)
    directory = ObjectDirectory(client, capacity_bytes=64 * 1024 * 1024)
    mb = 1024 * 1024
    admitted = []  # job label, in admission order
    job_of = {}

    async def scenario():
        pm = PullManager(
            node_id="n", session=session, shm=client, directory=directory,
            get_view=lambda: {}, get_gcs=lambda: None,
        )

        async def fake_transfer(oid, source_addr, nbytes, transport,
                                deadline):
            admitted.append(job_of[oid.binary()])
            await asyncio.sleep(0.1)
            return {"ok": True}

        pm._transfer = fake_transfer
        a_pulls = []
        for _ in range(6):
            oid = ObjectID.from_random()
            job_of[oid.binary()] = "A"
            a_pulls.append(asyncio.create_task(
                pm.pull(oid, None, nbytes=mb, job_id="jobA")
            ))
        await asyncio.sleep(0.03)  # 2 admit (2 MB budget), 4 park FIFO
        oid_b = ObjectID.from_random()
        job_of[oid_b.binary()] = "B"
        b_pull = asyncio.create_task(
            pm.pull(oid_b, None, nbytes=mb, job_id="jobB")
        )
        results = await asyncio.gather(*a_pulls, b_pull)
        assert all(r["ok"] for r in results), results

    try:
        asyncio.run(scenario())
        # B was submitted seventh but must admit right after the first
        # slot frees: ahead of the 4 parked A pulls
        assert admitted.index("B") <= 3, admitted
        assert admitted.count("A") == 6 and admitted.count("B") == 1
    finally:
        _config.pull_max_inflight_bytes = saved
        client.destroy()


# ------------------------------------------------------- cluster level
def test_proactive_spill_restore_on_get_and_metrics():
    """Aggressive-spill raylet: produced objects move to disk in the
    background; a later consumer restores them transparently (byte-
    identical) and the spill/restore counters + metrics series record
    both directions."""
    procs, gcs, sessions, saved = _start_split_cluster(
        [
            {"name": "node-a", "num_cpus": 1},
            {"name": "node-b", "num_cpus": 1, "resources": {"b": 1}},
        ],
        extra_env=_SPILL_ENV,
    )
    ray_tpu.init(address=gcs, _node_name="node-a")
    try:
        @ray_tpu.remote(resources={"b": 1})
        def produce():
            import numpy as _np

            return _np.random.default_rng(21).integers(
                0, 255, size=1024 * 1024, dtype=_np.uint8
            )

        ref = produce.remote()
        ray_tpu.wait([ref], timeout=60)
        core = _core()
        b_addr = _raylet_addr_of(core, "node-b")

        deadline = time.monotonic() + 30
        st = {}
        while time.monotonic() < deadline:
            st = _store_stats(core, b_addr)
            if st["num_spills"] >= 1 and st["states"]["spilled"] >= 1:
                break
            time.sleep(0.2)
        assert st.get("num_spills", 0) >= 1, st
        assert st["used_bytes"] == 0, st  # shm copy unlinked after spill
        # spill metadata registered at the GCS for the death path
        locs = _locations(core, ref.id.hex())
        assert any(h["node_id"] == "node-b" and h["spilled"]
                   for h in locs), locs

        @ray_tpu.remote(resources={"b": 1})
        def consume(x):
            return int(x.sum()) % 1_000_003

        want = int(np.random.default_rng(21).integers(
            0, 255, size=1024 * 1024, dtype=np.uint8
        ).sum()) % 1_000_003
        assert ray_tpu.get(consume.remote(ref), timeout=120) == want
        st = _store_stats(core, b_addr)
        assert st["num_restores"] >= 1, st

        # the new metric series flow through the raylet flush into the
        # GCS timeseries (KNOWN_METRICS names, RT006-checked)
        from ray_tpu.util import state

        deadline = time.monotonic() + 20
        seen = set()
        while time.monotonic() < deadline:
            for sample in state.get_metrics_timeseries(limit=200):
                for series in sample.get("series", ()):
                    seen.add(series.get("name"))
            if {"object_spilled_total", "object_restored_total"} <= seen:
                break
            time.sleep(0.5)
        assert "object_spilled_total" in seen, sorted(seen)
        assert "object_restored_total" in seen, sorted(seen)
    finally:
        _teardown_split_cluster(procs, sessions, saved)


def test_spill_delete_deregisters_and_pull_falls_through():
    """Satellite: freeing a spill-backed copy must deregister its GCS
    location exactly like eviction does — a later pull for the object
    skips the stale holder and lands on the next one."""
    procs, gcs, sessions, saved = _start_split_cluster(
        [
            {"name": "node-a", "num_cpus": 1},
            {"name": "node-b", "num_cpus": 1, "resources": {"b": 1}},
        ],
        extra_env=_SPILL_ENV,
    )
    ray_tpu.init(address=gcs, _node_name="node-a")
    try:
        want = np.random.default_rng(23).integers(
            0, 255, size=1024 * 1024, dtype=np.uint8
        )

        @ray_tpu.remote(resources={"b": 1})
        def produce():
            import numpy as _np

            return _np.random.default_rng(23).integers(
                0, 255, size=1024 * 1024, dtype=_np.uint8
            )

        ref = produce.remote()
        got = ray_tpu.get(ref, timeout=120)  # node-a now holds a SECONDARY
        np.testing.assert_array_equal(got, want)
        core = _core()
        b_addr = _raylet_addr_of(core, "node-b")
        oid_hex = ref.id.hex()

        # wait until both holders are registered (node-b's spill sweep
        # also lands its spill metadata)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            locs = _locations(core, oid_hex)
            if {h["node_id"] for h in locs} >= {"node-a", "node-b"}:
                break
            time.sleep(0.2)
        assert {h["node_id"] for h in locs} >= {"node-a", "node-b"}, locs

        # free the (spilled) primary on node-b -> its location entry,
        # spill registration included, must go
        async def free_on_b():
            conn = await core._conn_to(b_addr, kind="raylet")
            return await conn.call(
                "free_objects", oids_hex=[oid_hex], timeout=30
            )

        assert core.io.run(free_on_b(), timeout=60)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            locs = _locations(core, oid_hex)
            if all(h["node_id"] != "node-b" for h in locs):
                break
            time.sleep(0.2)
        assert all(h["node_id"] != "node-b" for h in locs), locs
        assert any(h["node_id"] == "node-a" for h in locs), locs

        # a fresh pull on node-b consults the location table: the stale
        # self-entry is gone, so it falls through to node-a's copy
        sealed_nbytes = next(
            h["nbytes"] for h in locs if h["node_id"] == "node-a"
        )

        async def pull_back():
            conn = await core._conn_to(b_addr, kind="raylet")
            return await conn.call(
                "pull_object", oid_hex=oid_hex, source_addr=None,
                nbytes=sealed_nbytes, timeout=120,
            )

        reply = core.io.run(pull_back(), timeout=120)
        assert reply.get("ok"), reply

        @ray_tpu.remote(resources={"b": 1})
        def checksum(x):
            return int(x.sum())

        assert ray_tpu.get(checksum.remote(ref), timeout=120) == \
            int(want.sum())
    finally:
        _teardown_split_cluster(procs, sessions, saved)


@pytest.mark.chaos(timeout=240)
def test_kill_primary_holder_spill_adoption_restores_bytes():
    """Dead-node restore: SIGKILL the raylet holding the ONLY in-memory/
    spilled copy while the owner's ref is live. The GCS death path hands
    the dead node's spill files to a surviving raylet; the owner's get()
    re-anchors to the adopter and lands byte-identical content. The
    producing resource dies with the node, so lineage CANNOT save this —
    only spill adoption can."""
    procs, gcs, sessions, saved = _start_split_cluster(
        [
            {"name": "node-a", "num_cpus": 1},
            {"name": "node-b", "num_cpus": 1, "resources": {"b": 1}},
            {"name": "node-c", "num_cpus": 1, "resources": {"c": 1}},
        ],
        extra_env=_SPILL_ENV,
    )
    ray_tpu.init(address=gcs, _node_name="node-a")
    try:
        rng_seed = 29
        want = np.random.default_rng(rng_seed).integers(
            0, 255, size=1024 * 1024, dtype=np.uint8
        )

        @ray_tpu.remote(resources={"b": 1})
        def produce(seed):
            import numpy as _np

            return _np.random.default_rng(seed).integers(
                0, 255, size=1024 * 1024, dtype=_np.uint8
            )

        ref = produce.remote(rng_seed)
        ray_tpu.wait([ref], timeout=60)
        core = _core()
        b_addr = _raylet_addr_of(core, "node-b")
        oid_hex = ref.id.hex()

        # wait for the spill sweep to persist the primary AND register
        # its spill metadata — the only thing that survives the SIGKILL
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            locs = _locations(core, oid_hex)
            if any(h["node_id"] == "node-b" and h["spilled"] for h in locs):
                break
            time.sleep(0.2)
        assert any(h["node_id"] == "node-b" and h["spilled"]
                   for h in locs), locs

        # SIGKILL the primary holder (procs[0] is the GCS)
        procs.procs[2].kill()
        procs.procs[2].wait(timeout=10)

        # health-check death (~5s) -> adoption: a holder OTHER than
        # node-b appears in the location table
        deadline = time.monotonic() + 60
        adopted = []
        while time.monotonic() < deadline:
            locs = _locations(core, oid_hex)
            adopted = [h for h in locs if h["node_id"] != "node-b"]
            if adopted:
                break
            time.sleep(0.5)
        assert adopted, f"no surviving raylet adopted the spill: {locs}"

        got = ray_tpu.get(ref, timeout=120)
        np.testing.assert_array_equal(got, want)
    finally:
        _teardown_split_cluster(procs, sessions, saved)


@pytest.mark.chaos(timeout=240)
def test_kill_primary_holder_falls_back_to_lineage():
    """Dead-node last resort: the holder dies BEFORE any spill/secondary
    exists (default spill threshold, cold loop never ran). No copy
    survives anywhere, so the owner must fall to lineage reconstruction —
    the task re-executes on the surviving node with the same resource —
    and get() still lands byte-identical. Never a hang."""
    procs, gcs, sessions, saved = _start_split_cluster([
        {"name": "node-a", "num_cpus": 1},
        {"name": "node-b", "num_cpus": 1, "resources": {"w": 1, "b": 1}},
        {"name": "node-c", "num_cpus": 1, "resources": {"w": 1}},
    ])
    ray_tpu.init(address=gcs, _node_name="node-a")
    try:
        want = np.random.default_rng(31).integers(
            0, 255, size=512 * 1024, dtype=np.uint8
        )

        # resources={"b": 1} pins the FIRST execution to node-b; the
        # retry spec only needs "w", which node-c also offers
        @ray_tpu.remote(resources={"w": 0.5})
        def produce(seed):
            import numpy as _np

            return _np.random.default_rng(seed).integers(
                0, 255, size=512 * 1024, dtype=_np.uint8
            )

        @ray_tpu.remote(resources={"b": 1})
        def block():
            return True

        # occupy node-b... actually pin production by occupying node-c's
        # "w" first so produce lands on node-b deterministically
        @ray_tpu.remote(resources={"w": 1})
        def hold_w(sec):
            import time as _t

            _t.sleep(sec)
            return True

        holders = [hold_w.remote(4.0), hold_w.remote(4.0)]
        time.sleep(0.5)  # both w-nodes briefly saturated
        del holders
        ref = produce.remote(31)
        ray_tpu.wait([ref], timeout=60)
        core = _core()
        loc = core.locations.get(ref.id)
        assert loc is not None
        victim = loc["node_id"]
        assert victim in ("node-b", "node-c"), loc
        victim_idx = {"node-b": 2, "node-c": 3}[victim]

        procs.procs[victim_idx].kill()
        procs.procs[victim_idx].wait(timeout=10)

        t0 = time.monotonic()
        got = ray_tpu.get(ref, timeout=180)
        np.testing.assert_array_equal(got, want)
        assert time.monotonic() - t0 < 170, "get() nearly hung"
    finally:
        _teardown_split_cluster(procs, sessions, saved)


def test_pin_keeps_primary_under_pull_pressure():
    """End-to-end pinning: with the store too small for everything, owner-
    pinned primaries spill (never drop) while unpinned secondary pull
    caches evict first — and every live ref still gets byte-identical
    data back."""
    procs, gcs, sessions, saved = _start_split_cluster([
        {"name": "node-a", "num_cpus": 1, "store_mb": 3},
        {"name": "node-b", "num_cpus": 1, "resources": {"b": 1}},
    ])
    ray_tpu.init(address=gcs, _node_name="node-a")
    try:
        @ray_tpu.remote(resources={"b": 1})
        def produce(fill):
            return np.full(1024 * 1024, fill, dtype=np.uint8)

        refs = [produce.remote(i) for i in range(5)]
        for i, ref in enumerate(refs):  # pull everything through node-a
            assert ray_tpu.get(ref, timeout=120)[0] == i
        core = _core()
        st = _store_stats(core)
        assert st["used_bytes"] <= st["capacity_bytes"], st
        assert st["num_evicted"] >= 1, st
        # every ref is still readable and correct after the pressure
        for i, ref in enumerate(refs):
            got = ray_tpu.get(ref, timeout=120)
            assert got[0] == i and got.nbytes == 1024 * 1024
    finally:
        _teardown_split_cluster(procs, sessions, saved)
