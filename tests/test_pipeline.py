"""Pipeline parallelism (parallel/pipeline.py).

The reference has no PP at all (SURVEY §2.10 "absent — must be built new"),
so there is no behavior to mirror; these tests pin the contract instead:
a pp>1 mesh computes THE SAME function as pp=1 — same loss, same grads —
with the layer stack sharded over pp and a GPipe microbatch schedule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import gpt2
from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.parallel.pipeline import pipeline_apply, stages_from_layers
from ray_tpu.train.train_step import make_gpt2_train_step, synthetic_batch


def test_pipeline_apply_matches_sequential(cpu_mesh8):
    """pipeline_apply == applying the stages one after another."""
    P_, L, D = 4, 8, 16
    rng = np.random.default_rng(0)
    layers = jnp.asarray(rng.normal(size=(L, D, D)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.normal(size=(8, D)), jnp.float32)

    def stage_fn(ws, h):
        def body(h, w):
            return jnp.tanh(h @ w), None

        h, _ = jax.lax.scan(body, h, ws)
        return h

    # sequential reference: all L layers in order
    expect = stage_fn(layers, x)

    spec = mesh_lib.MeshSpec(pp=P_, dp=2)
    mesh = mesh_lib.make_mesh(spec, cpu_mesh8)
    got = jax.jit(
        lambda ws, x: pipeline_apply(
            stage_fn, stages_from_layers(ws, P_), x,
            num_stages=P_, num_microbatches=4, mesh=mesh,
        )
    )(layers, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), rtol=1e-5, atol=1e-6)


def test_pipeline_apply_grads_match(cpu_mesh8):
    P_, L, D = 2, 4, 8
    rng = np.random.default_rng(1)
    layers = jnp.asarray(rng.normal(size=(L, D, D)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.normal(size=(4, D)), jnp.float32)

    def stage_fn(ws, h):
        def body(h, w):
            return jnp.tanh(h @ w), None

        h, _ = jax.lax.scan(body, h, ws)
        return h

    def loss_seq(ws):
        return jnp.sum(stage_fn(ws, x) ** 2)

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(pp=P_), cpu_mesh8[:P_])

    def loss_pp(ws):
        y = pipeline_apply(
            stage_fn, stages_from_layers(ws, P_), x,
            num_stages=P_, num_microbatches=2, mesh=mesh,
        )
        return jnp.sum(y ** 2)

    g_seq = jax.grad(loss_seq)(layers)
    g_pp = jax.jit(jax.grad(loss_pp))(layers)
    np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq), rtol=1e-4, atol=1e-6)


@pytest.fixture
def f32_cfg():
    # f32 end to end for a tight pp-vs-no-pp comparison
    return gpt2.gpt2_tiny(dtype=jnp.float32, pipeline_microbatches=4)


def _loss_and_gnorm(cfg, mesh, batch):
    bundle = make_gpt2_train_step(cfg, mesh=mesh, rng=jax.random.PRNGKey(0))
    _, m = bundle.step_fn(bundle.state, batch)
    return float(m["loss"]), float(m["grad_norm"]), bundle


def test_gpt2_pp2_matches_pp1(cpu_mesh8, f32_cfg):
    """Full train step on a dp2/pp2 mesh == single-device step: same loss &
    grad norm on identical data (same init seed), layer stack pp-sharded."""
    batch = synthetic_batch(f32_cfg, global_batch=8)

    mesh1 = mesh_lib.single_device_mesh(cpu_mesh8[0])
    loss1, g1, _ = _loss_and_gnorm(f32_cfg, mesh1, batch)

    mesh2 = mesh_lib.make_mesh(mesh_lib.MeshSpec(dp=2, pp=2), cpu_mesh8[:4])
    loss2, g2, bundle = _loss_and_gnorm(f32_cfg, mesh2, batch)

    assert np.isfinite(loss2)
    np.testing.assert_allclose(loss2, loss1, rtol=1e-5)
    np.testing.assert_allclose(g2, g1, rtol=1e-4)
    # the stacked layer dim must actually be sharded over pp
    qkv = bundle.state["params"]["blocks"]["qkv_w"]
    assert "pp" in str(qkv.sharding.spec), qkv.sharding


def test_gpt2_pp_with_tp(cpu_mesh8):
    """pp composes with tp on the same mesh (GSPMD handles tp inside stages)."""
    cfg = gpt2.gpt2_tiny(dtype=jnp.float32, pipeline_microbatches=2)
    batch = synthetic_batch(cfg, global_batch=4)

    mesh1 = mesh_lib.single_device_mesh(cpu_mesh8[0])
    loss1, _, _ = _loss_and_gnorm(cfg, mesh1, batch)

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(pp=2, tp=2, dp=2), cpu_mesh8)
    loss, _, _ = _loss_and_gnorm(cfg, mesh, batch)
    np.testing.assert_allclose(loss, loss1, rtol=1e-5)


def test_pipeline_microbatch_validation(cpu_mesh8):
    cfg = gpt2.gpt2_tiny(dtype=jnp.float32, pipeline_microbatches=3)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(pp=2), cpu_mesh8[:2])
    bundle = make_gpt2_train_step(cfg, mesh=mesh, rng=jax.random.PRNGKey(0))
    batch = synthetic_batch(cfg, global_batch=4)  # 4 % 3 != 0
    with pytest.raises(ValueError, match="not divisible"):
        bundle.step_fn(bundle.state, batch)


def test_pipeline_moe_unsupported(cpu_mesh8):
    cfg = gpt2.gpt2_tiny(dtype=jnp.float32, moe_experts=4, moe_top_k=2)
    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec(pp=2), cpu_mesh8[:2])
    with pytest.raises(NotImplementedError, match="pipeline"):
        make_gpt2_train_step(cfg, mesh=mesh, rng=jax.random.PRNGKey(0))
