"""C++ user API (cpp/) end-to-end: build the client library + demo with g++,
run the demo against a live cluster's ray:// proxy, assert its output.

Parity: the reference ships a C++ API (cpp/) and a thin Ray Client
(python/ray/util/client/); our C++ driver is a thin client over the same
proxy (see cpp/include/ray_tpu/ray_tpu.h for the design note).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPP = os.path.join(REPO, "cpp")
DEMO = os.path.join(CPP, "build", "xlang_demo")


def _build():
    subprocess.run(["bash", os.path.join(CPP, "build.sh")], check=True,
                   capture_output=True)


@pytest.fixture(scope="module")
def client_server():
    import ray_tpu
    from ray_tpu.client import ClientServer

    _build()  # once per module; both tests run the same artifact
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    server = ClientServer(host="127.0.0.1", port=0)
    addr = server.start()
    host, port = addr.rsplit(":", 1)
    yield host, int(port)
    server.stop()
    ray_tpu.shutdown()


def test_cpp_demo_end_to_end(client_server):
    from ray_tpu.core import rpc

    host, port = client_server
    token = rpc.get_auth_token() or ""
    env = {**os.environ,
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}
    out = subprocess.run(
        [DEMO, host, str(port), token],
        capture_output=True, timeout=180, env=env,
    )
    text = out.stdout.decode()
    assert out.returncode == 0, (text, out.stderr.decode())
    lines = text.strip().splitlines()
    assert lines[0].startswith("connected version=")
    assert lines[1] == "roundtrip OK"
    assert lines[2] == "add=42"
    assert lines[3] == "the=3 words=8"          # word_stats over the demo text
    assert lines[4] == "wait ready=1 pending=0"
    assert lines[5] == "done"


def test_cpp_demo_rejects_bad_token(client_server):
    host, port = client_server
    out = subprocess.run(
        [DEMO, host, str(port), "wrong-token"],
        capture_output=True, timeout=60,
    )
    # the server closes unauthenticated connections before dispatch; the
    # client must fail loudly, not hang or succeed
    assert out.returncode != 0
