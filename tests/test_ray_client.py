"""Ray Client: thin drivers over ray:// (parity: python/ray/util/client/).

The server side owns real objects/actors; clients hold opaque refs and
proxy every call — including refs nested inside task args.
"""

import os
import subprocess
import sys
import textwrap

import pytest


@pytest.fixture(scope="module")
def client_cluster():
    import ray_tpu
    from ray_tpu.client import ClientServer

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    server = ClientServer(host="127.0.0.1", port=0)
    server.start()
    yield ray_tpu, server
    server.stop()
    ray_tpu.shutdown()


def test_client_backend_roundtrip(client_cluster):
    """Drive the ClientBackend protocol directly: put/get, tasks with
    nested refs, actors, named resources, wait."""
    _, server = client_cluster
    from ray_tpu.client import ClientBackend
    from ray_tpu.core.options import RemoteOptions

    b = ClientBackend(f"ray://{server.address}")
    try:
        # put/get
        ref = b.put({"x": 41})
        assert b.get([ref], None) == [{"x": 41}]

        # task with a client ref nested inside its args
        def add(d, y):
            return d["x"] + y

        (out,) = b.submit_task(add, ({"x": 41}, 1), {}, RemoteOptions())
        assert b.get([out], 60) == [42]
        (out2,) = b.submit_task(
            lambda d, y: d["x"] + y, (ref, 1), {}, RemoteOptions()
        )
        assert b.get([out2], 60) == [42]

        # wait
        ready, pending = b.wait([out, out2], 2, 60, True)
        assert len(ready) == 2 and not pending

        # actors
        class Counter:
            def __init__(self, start):
                self.n = start

            def inc(self, k):
                self.n += k
                return self.n

        aid = b.create_actor(Counter, (10,), {}, RemoteOptions(name="cl-ctr"))
        (r1,) = b.submit_actor_task(aid, "inc", (5,), {}, RemoteOptions())
        (r2,) = b.submit_actor_task(aid, "inc", (5,), {}, RemoteOptions())
        assert b.get([r1, r2], 60) == [15, 20]
        # named-actor lookup through the proxy
        aid2 = b.get_named_actor("cl-ctr", None)
        (r3,) = b.submit_actor_task(aid2, "inc", (1,), {}, RemoteOptions())
        assert b.get([r3], 60) == [21]
        b.kill_actor(aid, True)

        assert b.cluster_resources().get("CPU", 0) >= 2
        assert b.info["ray_version"]
    finally:
        b.shutdown()


def test_thin_client_subprocess(client_cluster):
    """A separate process uses the FULL public API via ray:// — it never
    joins the cluster (no raylet/GCS connection), everything proxies."""
    _, server = client_cluster
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {repo!r})
        import ray_tpu
        ray_tpu.init("ray://{server.address}")

        @ray_tpu.remote
        def square(x):
            return x * x

        refs = [square.remote(i) for i in range(5)]
        print("TASKS", ray_tpu.get(refs, timeout=60))

        @ray_tpu.remote
        class Acc:
            def __init__(self):
                self.total = 0
            def add(self, v):
                self.total += v
                return self.total

        a = Acc.remote()
        print("ACTOR", ray_tpu.get([a.add.remote(i) for i in (1, 2, 3)],
                                   timeout=60))
        obj = ray_tpu.put([1, 2, 3])
        print("PUT", ray_tpu.get(obj))
        ray_tpu.shutdown()
        print("CLIENT_DONE")
    """)
    out = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, timeout=180, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert "TASKS [0, 1, 4, 9, 16]" in out.stdout, out.stdout + out.stderr
    assert "ACTOR [1, 3, 6]" in out.stdout
    assert "PUT [1, 2, 3]" in out.stdout
    assert "CLIENT_DONE" in out.stdout
