"""Module-level functions the C++ cross-language demo calls by descriptor
("tests.xlang_funcs:name" — see cpp/examples/xlang_demo.cc and
ClientServer.handle_submit_named_task)."""


def add(a, b):
    return a + b


def word_stats(text):
    words = text.split()
    out = {}
    for w in words:
        out[w] = out.get(w, 0) + 1
    out["__total__"] = len(words)
    return out


def slow_echo(x, delay):
    import time

    time.sleep(delay)
    return x
