"""Dispatch-plane wire path (PR 6): frame coalescing, BATCH frames,
out-of-band zero-copy segments, backpressure, and the v2 version handshake
(ray_tpu/core/rpc.py).

These run the RPC plane directly (in-process server + client on a private
event loop) — no cluster needed, so they are cheap enough for tier-1.
"""

import asyncio
import pickle
import struct

import numpy as np
import pytest

from ray_tpu.core import rpc
from ray_tpu.core.config import _config


def _run(coro, timeout=60):
    async def guarded():
        return await asyncio.wait_for(coro, timeout)

    return asyncio.run(guarded())


class _Recorder:
    """RPC handler recording arrival order of tagged requests."""

    def __init__(self):
        self.order = []

    def handle_mark(self, conn, tag):
        # synchronous handler: recorded the moment the dispatch task runs,
        # which asyncio orders by creation == frame/batch order
        self.order.append(tag)
        return tag

    def handle_echo(self, conn, data):
        return data

    def handle_echo_oob(self, conn, data):
        raw = rpc.unwrap_oob(data)
        return rpc.Oob(raw)


async def _server_and_conn(handler):
    server = rpc.RpcServer(handler, host="127.0.0.1", port=0)
    await server.start()
    conn = await rpc.connect(server.address, name="test-client")
    return server, conn


# ---------------------------------------------------------------- ordering
def test_coalescing_preserves_fifo_order():
    """Mixed direct / batched / notify sends on one connection arrive in
    enqueue order: staged BATCH groups drain before any later direct frame,
    and BATCH frames dispatch their requests in list order."""

    async def run():
        rec = _Recorder()
        server, conn = await _server_and_conn(rec)
        try:
            futs = []
            # same-tick mix: batched requests stage, direct frames must not
            # overtake them, one-way notifies ride the same outbox
            futs.append(await conn.call_start_batched("mark", tag="b0"))
            futs.append(await conn.call_start("mark", tag="d1"))
            futs.append(await conn.call_start_batched("mark", tag="b2"))
            futs.append(await conn.call_start_batched("mark", tag="b3"))
            await conn.notify_batched("mark", tag="n4")
            futs.append(await conn.call_start("mark", tag="d5"))
            await asyncio.gather(*futs)
            # the notify has no reply; wait for its side effect
            for _ in range(200):
                if len(rec.order) >= 6:
                    break
                await asyncio.sleep(0.01)
            assert rec.order == ["b0", "d1", "b2", "b3", "n4", "d5"]
        finally:
            await conn.close()
            await server.close()

    _run(run())


def test_batched_requests_share_one_frame():
    """Requests staged in one loop tick coalesce: the receiving side sees
    fewer frames than requests, and the coalesced counter says so."""

    async def run():
        rec = _Recorder()
        server, conn = await _server_and_conn(rec)
        try:
            n = 32
            futs = [
                await conn.call_start_batched("mark", tag=i) for i in range(n)
            ]
            assert await asyncio.gather(*futs) == list(range(n))
            assert rec.order == list(range(n))
            # all n staged before the first flush tick → one BATCH frame
            assert conn.stats["rpc_frames_coalesced"] >= n - 1
            assert conn.stats["rpc_frames_sent"] < n
        finally:
            await conn.close()
            await server.close()

    _run(run())


# ------------------------------------------------------------- zero-copy
def test_oob_round_trip_byte_identical():
    """Oob-wrapped bytes and numpy arrays ride the segment table and come
    back byte-identical through a live server round trip."""

    async def run():
        server, conn = await _server_and_conn(_Recorder())
        try:
            blob = bytes(range(256)) * 1024  # 256 KiB, > oob threshold
            out = await conn.call("echo_oob", data=rpc.Oob(blob), timeout=30)
            got = rpc.unwrap_oob(out)
            assert isinstance(got, memoryview)  # zero-copy view, not a copy
            assert bytes(got) == blob
            assert conn.stats["rpc_oob_bytes"] >= len(blob)

            # memoryview source: written straight from the view's memory
            src = memoryview(blob)[1024:200 * 1024]
            out = await conn.call("echo_oob", data=rpc.Oob(src), timeout=30)
            assert bytes(rpc.unwrap_oob(out)) == bytes(src)

            # numpy arrays split their data buffer out-of-band natively
            # (protocol-5 __reduce_ex__), no Oob wrapper needed
            arr = np.arange(64 * 1024, dtype=np.float32).reshape(256, 256)
            out = await conn.call("echo", data=arr, timeout=30)
            assert out.dtype == arr.dtype and out.shape == arr.shape
            assert np.array_equal(out, arr)
            assert out.tobytes() == arr.tobytes()
        finally:
            await conn.close()
            await server.close()

    _run(run())


def test_encode_decode_frame_oob_exact_bytes():
    """Frame encode → decode is byte-exact for every out-of-band source
    kind, and small buffers stay in-band (no segment table entries)."""
    arr = np.arange(32 * 1024, dtype=np.int64)  # 256 KiB data buffer
    blob = b"\xab" * (128 * 1024)
    msg = (rpc.REQUEST, 7, "m", {"a": arr, "b": rpc.Oob(blob), "s": b"tiny"})
    wire = rpc.encode_frame_bytes(msg)
    n = int.from_bytes(wire[:8], "little")
    assert n == len(wire) - 8
    mtype, mid, method, payload = rpc._decode_body(wire[8:])
    assert (mtype, mid, method) == (rpc.REQUEST, 7, "m")
    assert payload["a"].tobytes() == arr.tobytes()
    assert bytes(rpc.unwrap_oob(payload["b"])) == blob
    assert payload["s"] == b"tiny"

    small = (rpc.REQUEST, 1, "m", {"x": b"y" * 100})
    wire = rpc.encode_frame_bytes(small)
    # nbuf field right after the 8-byte length prefix must be zero
    assert struct.unpack_from("<I", wire, 8)[0] == 0


# ----------------------------------------------------------- backpressure
class _StallWriter:
    """StreamWriter stand-in whose drain() parks until released."""

    def __init__(self):
        self.release = None  # asyncio.Event, created on loop
        self.written = []

    def write(self, data):
        self.written.append(bytes(data))

    async def drain(self):
        await self.release.wait()

    def close(self):
        pass

    def get_extra_info(self, key):
        return None


def test_backpressure_bound_blocks_producers():
    """Once rpc_max_outstanding_bytes of un-flushed frames queue behind a
    stalled peer, further sends block until the flusher drains — and then
    complete."""

    async def run():
        saved = _config.rpc_max_outstanding_bytes
        _config.rpc_max_outstanding_bytes = 1 << 16  # floor: 64 KiB
        writer = _StallWriter()
        writer.release = asyncio.Event()
        conn = rpc.Connection(None, writer, name="bp-test")
        try:
            payload = b"z" * (80 * 1024)  # each frame > the 64 KiB bound
            # frame 1: taken by the flusher immediately, stalls in drain()
            await conn.notify("m", data=rpc.Oob(payload))
            await asyncio.sleep(0.05)
            assert writer.written, "flusher must have started writing"
            # frame 2: queues in the outbox (un-flushed bytes now > bound)
            await conn.notify("m", data=rpc.Oob(payload))
            # frame 3: must BLOCK on the backpressure bound
            t3 = asyncio.ensure_future(
                conn.notify("m", data=rpc.Oob(payload)))
            await asyncio.sleep(0.1)
            assert not t3.done(), "producer must block past the bound"
            # release the peer: flusher drains, waiters wake, send completes
            writer.release.set()
            await asyncio.wait_for(t3, 10)
            for _ in range(200):
                if conn.stats["rpc_frames_sent"] == 3 and not conn._outbox:
                    break
                await asyncio.sleep(0.01)
            assert conn.stats["rpc_frames_sent"] == 3
            total = sum(len(c) for c in writer.written)
            assert total == conn.stats["rpc_bytes_sent"]
        finally:
            _config.rpc_max_outstanding_bytes = saved
            await conn.close()

    _run(run())


# ------------------------------------------------------ version handshake
def test_v1_era_bare_frame_rejected(caplog):
    """A pre-v2 peer (no preamble, single pickled frame) is closed at the
    handshake with a clear logged reason — its bytes are never unpickled."""
    import logging

    async def run():
        server = rpc.RpcServer(_Recorder(), host="127.0.0.1", port=0)
        await server.start()
        saved = rpc._auth_token
        rpc._auth_token = None  # isolate the version gate from the token gate
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.port)
            # v1 wire format: length-prefixed pickle, no segment table,
            # no preamble
            data = pickle.dumps((0, 1, "mark", {"tag": "v1"}), protocol=5)
            writer.write(len(data).to_bytes(8, "little") + data)
            await writer.drain()
            got = await asyncio.wait_for(reader.read(1), 30)
            assert got == b"", "server must close v1-era peers"
            writer.close()
        finally:
            rpc._auth_token = saved
            await server.close()

    with caplog.at_level(logging.WARNING, logger="ray_tpu.core.rpc"):
        _run(run())
    assert any("preamble" in r.message for r in caplog.records), (
        "rejection must log a clear reason")


def test_wrong_version_preamble_rejected_with_reason(caplog):
    """A peer announcing a different protocol rev is refused with a log
    line naming both revs."""
    import logging

    async def run():
        server = rpc.RpcServer(_Recorder(), host="127.0.0.1", port=0)
        await server.start()
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.port)
            bad = b"RAYTPU-AUTH1 " + (rpc.get_auth_token() or "").encode()
            writer.write(len(bad).to_bytes(8, "little") + bad)
            await writer.drain()
            got = await asyncio.wait_for(reader.read(1), 30)
            assert got == b""
            writer.close()
        finally:
            await server.close()

    with caplog.at_level(logging.WARNING, logger="ray_tpu.core.rpc"):
        _run(run())
    assert any("version mismatch" in r.message for r in caplog.records)


# ----------------------------------------------------------- batched put
def test_put_many_round_trip_local(ray_start_local):
    import ray_tpu

    values = [b"x" * 64, {"k": 1}, list(range(10))]
    refs = ray_tpu.put_many(values)
    assert len(refs) == len(values)
    assert ray_tpu.get(refs) == values
    assert ray_tpu.put_many([]) == []


def test_put_many_round_trip_cluster(ray_start_regular):
    import ray_tpu

    values = [b"small", b"y" * (256 * 1024), {"n": 3}]  # inline + shm sizes
    refs = ray_tpu.put_many(values)
    assert ray_tpu.get(refs) == values
    # refs are real: usable as task args like any put() ref
    @ray_tpu.remote
    def length(x):
        return len(x)

    assert ray_tpu.get(length.remote(refs[1])) == 256 * 1024


# ------------------------------------------------------------ close path
def test_unflushed_outbox_fails_pending_typed():
    """Frames still in the outbox when the connection dies fail their
    response futures with the typed, retryable ConnectionLost."""

    async def run():
        writer = _StallWriter()
        writer.release = asyncio.Event()  # never set: peer wedged forever
        conn = rpc.Connection(None, writer, name="dead-test")
        fut1 = await conn.call_start("m", x=1)       # flushed, in drain()
        await asyncio.sleep(0.02)
        fut2 = await conn.call_start_batched("m", x=2)  # staged, un-flushed
        await conn._handle_close()
        for fut in (fut1, fut2):
            with pytest.raises(rpc.ConnectionLost):
                await fut
        # a send after close is refused with the same typed error
        with pytest.raises(rpc.ConnectionLost):
            await conn.call_start("m", x=3)

    _run(run())


# ------------------------------------------------------- vectored flushes
def test_advance_chunks_partial_write_resume():
    """advance_chunks resumes a partial gather-write at the exact byte:
    walking an arbitrary chunk list byte-by-byte reconstructs the stream
    with no duplication or loss — the frame-boundary integrity invariant
    under partial sendmsg/writev."""
    chunks = [
        b"abc",
        bytearray(b"defgh"),
        memoryview(np.arange(4, dtype=np.uint8)),
        b"",
        b"tail",
    ]
    whole = b"".join(bytes(memoryview(c).cast("B")) for c in chunks)
    for step in (1, 2, 3, 5, len(whole)):
        rest = list(chunks)
        out = b""
        while rest:
            take = min(step, sum(memoryview(c).nbytes for c in rest))
            # simulate the kernel accepting `take` bytes of the gather
            flat = b"".join(
                bytes(memoryview(c).cast("B")) for c in rest
            )
            out += flat[:take]
            rest = rpc.advance_chunks(rest, take)
        assert out == whole, f"step={step}"
    # fully-consumed list comes back empty
    assert rpc.advance_chunks([b"xy"], 2) == []


def test_vectored_flush_integrity_under_partial_writes():
    """Many frames — including multi-chunk OOB frames far larger than a
    socket buffer — pushed through one connection round-trip byte-identical
    and in order: the sendmsg fast path's partial writes resume mid-frame
    without corrupting frame boundaries."""

    async def run():
        rec = _Recorder()
        server, conn = await _server_and_conn(rec)
        try:
            futs = []
            blobs = []
            for i in range(30):
                if i % 3 == 0:
                    # multi-megabyte OOB payload: guaranteed to exceed the
                    # kernel buffer, forcing partial vectored writes
                    arr = np.full(300_000 + i, i % 251, dtype=np.uint8)
                    blobs.append(arr)
                    futs.append(await conn.call_start_batched(
                        "echo_oob", data=rpc.Oob(arr.data)
                    ))
                else:
                    blobs.append(bytes([i % 251]) * (i + 1))
                    futs.append(await conn.call_start_batched(
                        "echo", data=blobs[-1]
                    ))
            results = await asyncio.gather(*futs)
            for i, (blob, got) in enumerate(zip(blobs, results)):
                raw = rpc.unwrap_oob(got)
                assert bytes(memoryview(raw).cast("B")) == bytes(
                    memoryview(blob).cast("B")
                ), f"frame {i} corrupted"
        finally:
            await conn.close()
            await server.close()

    _run(run())


def test_adaptive_coalesce_delay_per_connection():
    """PR-13: the gather window adapts PER CONNECTION — a connection whose
    recent flushes carried many frames each (reply fan-in) stretches its
    delay to rpc_adaptive_coalesce_max_ms; an idle/request-response
    connection flushes on the next tick; adaptive off restores the fixed
    global delay for everyone."""
    from ray_tpu.core.config import _config
    from ray_tpu.core.rpc import Connection

    conn = Connection(None, None, name="test-adaptive")
    saved = (_config.rpc_adaptive_coalesce, _config.rpc_coalesce_delay_ms,
             _config.rpc_adaptive_coalesce_max_ms,
             _config.rpc_adaptive_coalesce_min_frames)
    try:
        _config.rpc_adaptive_coalesce = True
        _config.rpc_coalesce_delay_ms = 0.0
        _config.rpc_adaptive_coalesce_max_ms = 0.5
        _config.rpc_adaptive_coalesce_min_frames = 6.0
        # idle connection: no history -> immediate flush
        assert conn._coalesce_delay_s() == 0.0
        # busy connection: EWMA of frames/flush over the threshold
        conn._flush_ewma = 12.0
        assert conn._coalesce_delay_s() == 0.0005
        # decayed back under the threshold -> immediate again
        conn._flush_ewma = 2.0
        assert conn._coalesce_delay_s() == 0.0
        # adaptive off: the fixed floor applies regardless of busyness
        _config.rpc_adaptive_coalesce = False
        conn._flush_ewma = 50.0
        assert conn._coalesce_delay_s() == 0.0
        _config.rpc_coalesce_delay_ms = 1.0
        assert conn._coalesce_delay_s() == 0.001
        # fixed floor is never LOWERED by the adaptive path
        _config.rpc_adaptive_coalesce = True
        _config.rpc_coalesce_delay_ms = 2.0
        assert conn._coalesce_delay_s() == 0.002
    finally:
        (_config.rpc_adaptive_coalesce, _config.rpc_coalesce_delay_ms,
         _config.rpc_adaptive_coalesce_max_ms,
         _config.rpc_adaptive_coalesce_min_frames) = saved
