"""Ring attention vs full attention (fwd + bwd) on the virtual CPU mesh.

VERDICT r2 item 5 acceptance: ring == full attention on an 8-device mesh with
cp >= 2, and a GPT-2 step running with a cp axis.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel import mesh as mesh_lib


def ref_attention(q, k, v, causal=True):
    S, Skv = q.shape[1], k.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, Skv), dtype=bool))
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)
    ).astype(q.dtype)


def make_qkv(key, B=2, S=256, H=4, hd=32, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    return (
        jax.random.normal(k1, (B, S, H, hd), dtype),
        jax.random.normal(k2, (B, S, H, hd), dtype),
        jax.random.normal(k3, (B, S, H, hd), dtype),
    )


@pytest.mark.parametrize("cp", [2, 4])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full_forward(cpu_mesh8, cp, causal):
    from ray_tpu.ops.ring_attention import ring_attention_sharded

    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshSpec.for_devices(8, cp=cp), cpu_mesh8
    )
    q, k, v = make_qkv(jax.random.PRNGKey(0))
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, mesh, causal=causal, block_q=32, block_k=32
        )
    )(q, k, v)
    ref = ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ring_matches_full_backward(cpu_mesh8):
    from ray_tpu.ops.ring_attention import ring_attention_sharded

    mesh = mesh_lib.make_mesh(mesh_lib.MeshSpec.for_devices(8, cp=4), cpu_mesh8)
    q, k, v = make_qkv(jax.random.PRNGKey(1), B=1, S=128, H=2, hd=32)

    def loss_ring(q, k, v):
        o = ring_attention_sharded(
            q, k, v, mesh, causal=True, block_q=32, block_k=32
        )
        return jnp.sum(jnp.sin(o.astype(jnp.float32)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(ref_attention(q, k, v).astype(jnp.float32)))

    g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_ring_with_sharded_inputs(cpu_mesh8):
    """Inputs already laid out with batch on (dp, fsdp) and seq on cp — the
    exact activation sharding the GPT-2 train step produces."""
    from ray_tpu.ops.ring_attention import ring_attention_sharded

    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshSpec.for_devices(8, cp=2, fsdp=2), cpu_mesh8
    )
    q, k, v = make_qkv(jax.random.PRNGKey(2), B=4, S=128)
    sh = NamedSharding(mesh, P(("dp", "fsdp"), "cp", None, None))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))
    out = jax.jit(
        lambda q, k, v: ring_attention_sharded(
            q, k, v, mesh, block_q=32, block_k=32
        )
    )(q, k, v)
    ref = ref_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_gpt2_train_step_with_cp(cpu_mesh8):
    """Full GPT-2 train step over a mesh with cp=2: auto impl selects ring,
    loss is finite and matches the same step on a single device."""
    from ray_tpu.models import gpt2
    from ray_tpu.train.train_step import make_gpt2_train_step, synthetic_batch

    cfg = gpt2.gpt2_tiny()
    batch = synthetic_batch(cfg, global_batch=8)

    mesh = mesh_lib.make_mesh(
        mesh_lib.MeshSpec.for_devices(8, cp=2, tp=2, fsdp=2), cpu_mesh8
    )
    bundle = make_gpt2_train_step(cfg, mesh=mesh, rng=jax.random.PRNGKey(0))
    state, metrics = bundle.step_fn(bundle.state, batch)
    loss_cp = float(jax.device_get(metrics["loss"]))
    assert np.isfinite(loss_cp)

    ref_mesh = mesh_lib.single_device_mesh(cpu_mesh8[0])
    ref_bundle = make_gpt2_train_step(
        cfg, mesh=ref_mesh, rng=jax.random.PRNGKey(0)
    )
    _, ref_metrics = ref_bundle.step_fn(ref_bundle.state, batch)
    loss_ref = float(jax.device_get(ref_metrics["loss"]))
    assert abs(loss_cp - loss_ref) < 5e-3, (loss_cp, loss_ref)
