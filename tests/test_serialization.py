"""Serialization unit tests.

The regression this file guards: task/actor ARGUMENTS that are functions or
classes from modules workers can't import (test files, user scripts) must be
pickled by VALUE (reference semantics: function export via the GCS function
table, python/ray/_private/function_manager.py). Round-1 bug: _Pickler's
reducer_override returned NotImplemented, silently disabling cloudpickle's
function handling.
"""

import numpy as np
import pytest

from ray_tpu.core import serialization as ser


def roundtrip(value):
    return ser.loads(ser.dumps(value))


MODULE_CONSTANT = 41


def module_level_fn(x):
    return x + MODULE_CONSTANT


class ModuleLevelClass:
    def __init__(self, x):
        self.x = x

    def double(self):
        return self.x * 2


def test_roundtrip_basic_values():
    for v in [1, "a", None, {"k": [1, 2.5, b"bytes"]}, (1, 2), {3, 4}]:
        assert roundtrip(v) == v


def test_roundtrip_numpy_zero_copy_oob():
    arr = np.arange(100_000, dtype=np.float32).reshape(100, 1000)
    s = ser.serialize(arr)
    # big array goes out-of-band, payload stays small
    assert s.buffers, "large ndarray should be an out-of-band buffer"
    assert len(s.payload) < 10_000
    out = ser.deserialize(s)
    np.testing.assert_array_equal(out, arr)


def test_roundtrip_local_closure():
    y = 10

    def local_fn(x):
        return x + y

    fn = roundtrip(local_fn)
    assert fn(5) == 15


def test_roundtrip_lambda():
    fn = roundtrip(lambda x: x * 3)
    assert fn(4) == 12


def test_module_function_pickled_by_value():
    """Functions from this (unimportable-on-workers) test module must carry
    their code, not a module reference."""
    s = ser.serialize(module_level_fn)
    # by-value payload embeds the code object; by-reference would just be the
    # module+name string. Heuristic: by-value payloads mention the co_name.
    fn = ser.deserialize(s)
    assert fn(1) == 42
    # and the payload must not require importing this module on loads: strip
    # the module from sys.modules around deserialization to prove it.
    import sys

    mod = sys.modules.pop(__name__)
    try:
        fn2 = ser.loads(ser.dumps(module_level_fn))
        assert fn2(2) == 43
    finally:
        sys.modules[__name__] = mod


def test_module_class_pickled_by_value():
    import sys

    blob = ser.dumps(ModuleLevelClass)
    mod = sys.modules.pop(__name__)
    try:
        cls = ser.loads(blob)
        assert cls(21).double() == 42
    finally:
        sys.modules[__name__] = mod


def test_module_class_instance_pickled_by_value():
    import sys

    inst = ModuleLevelClass(7)
    blob = ser.dumps(inst)
    mod = sys.modules.pop(__name__)
    try:
        out = ser.loads(blob)
        assert out.double() == 14
    finally:
        sys.modules[__name__] = mod


def test_installed_packages_pickle_by_reference():
    """numpy functions must NOT be pickled by value (registry must be
    scoped to user modules and unregistered after serialize)."""
    import cloudpickle

    blob = ser.dumps(np.mean)
    assert len(blob) < 2000, "np.mean should pickle as a reference"
    # serialize() must not leave modules registered for by-value pickling
    assert not getattr(
        cloudpickle.cloudpickle, "_PICKLE_BY_VALUE_MODULES", {}
    ), "serialize leaked by-value module registrations"


def test_nested_function_in_container():
    payload = {"cb": module_level_fn, "data": [1, 2]}
    import sys

    blob = ser.dumps(payload)
    mod = sys.modules.pop(__name__)
    try:
        out = ser.loads(blob)
        assert out["cb"](0) == 41
        assert out["data"] == [1, 2]
    finally:
        sys.modules[__name__] = mod


def test_function_as_task_arg_on_cluster(ray_start_regular):
    """End-to-end: ship a test-module function as a task ARGUMENT."""
    import ray_tpu

    def apply_fn(f, x):
        return f(x)

    ref = ray_tpu.remote(apply_fn).remote(module_level_fn, 1)
    assert ray_tpu.get(ref, timeout=60) == 42


def test_class_as_actor_arg_on_cluster(ray_start_regular):
    import ray_tpu

    class Holder:
        def __init__(self, factory):
            self.obj = factory(5)

        def value(self):
            return self.obj.double()

    h = ray_tpu.remote(Holder).remote(ModuleLevelClass)
    assert ray_tpu.get(h.value.remote(), timeout=60) == 10
