"""SAC (discrete): max-entropy off-policy actor-critic.

Parity: rllib/algorithms/sac/ — learning regression in the tuned-example
spirit (CartPole episode_reward_mean >= 150 like the other algos).
"""

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


def test_sac_learner_update_mechanics():
    """One jitted update: losses finite, temperature moves toward the
    entropy target, polyak target actually tracks the online Q nets."""
    import jax

    from ray_tpu.rllib.algorithms.sac import SACLearner

    rng = np.random.default_rng(0)
    n, obs_dim, num_actions = 256, 4, 2
    batch = SampleBatch({
        SampleBatch.OBS: rng.normal(size=(n, obs_dim)).astype(np.float32),
        SampleBatch.ACTIONS: rng.integers(0, num_actions, n),
        SampleBatch.REWARDS: rng.normal(size=n).astype(np.float32),
        SampleBatch.NEXT_OBS: rng.normal(size=(n, obs_dim)).astype(np.float32),
        SampleBatch.TERMINATEDS: np.zeros(n, bool),
        SampleBatch.TRUNCATEDS: np.zeros(n, bool),
    })
    learner = SACLearner(obs_dim, num_actions, hiddens=(32,), lr=3e-3,
                         tau=0.05, seed=0)
    t0 = jax.tree.map(np.asarray, learner._state["target"])
    m = None
    for _ in range(20):
        m = learner.update(batch)
    assert np.isfinite(m["loss"]) and np.isfinite(m["alpha"])
    assert 0.0 < m["policy_entropy"] <= np.log(num_actions) + 1e-6
    assert m["td_errors"].shape == (n,)
    # targets moved toward the online nets (polyak, not frozen)
    t1 = learner._state["target"]
    moved = jax.tree.leaves(
        jax.tree.map(lambda a, b: float(np.abs(np.asarray(b) - a).max()), t0, t1)
    )
    assert max(moved) > 0.0

    # weights round-trip carries ONLY the policy module (what runners need)
    w = learner.get_weights()
    assert set(w.keys()) == {"pi", "vf"}
    learner.set_weights(w)


def test_sac_learns_cartpole():
    """Learning regression: stochastic-policy exploration + twin soft-Q +
    auto temperature reaches >= 150 on CartPole."""
    from ray_tpu.rllib.algorithms import SACConfig

    algo = (
        SACConfig()
        .environment("CartPole-v1", num_envs_per_worker=8)
        .rollouts(num_rollout_workers=0, rollout_fragment_length=16)
        .training(
            lr=3e-3,
            train_batch_size=256,
            learning_starts=500,
            train_intensity=8,
            hiddens=(64, 64),
        )
        .debugging(seed=0)
        .build()
    )
    best = -np.inf
    for i in range(600):
        res = algo.train()
        best = max(best, res.get("episode_reward_mean", -np.inf))
        if best >= 150:
            break
    assert best >= 150, f"SAC failed to learn CartPole: best={best}"
