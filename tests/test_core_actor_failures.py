"""Actor death / kill semantics (reference analog: test_actor_failures.py)."""

import time

import pytest


@pytest.mark.chaos(timeout=60)
def test_kill_resolves_pending_refs(ray_start_local):
    """Deterministic chaos-plan replacement of the old sleep-then-kill
    pattern: the actor dies exactly when it dispatches its first nap(),
    and BOTH the in-flight and the queued call resolve as ActorDiedError."""
    ray = ray_start_local
    from ray_tpu.testing import chaos

    @ray.remote
    class Slow:
        def nap(self):
            time.sleep(30)
            return "done"

    a = Slow.remote()
    with chaos.plan(0).kill_actor(match="Slow.nap", after_calls=1) as p:
        ref = a.nap.remote()
        queued = a.nap.remote()  # sits in the queue behind the dying call
        with pytest.raises(ray.exceptions.ActorDiedError):
            ray.get(queued, timeout=5)
        with pytest.raises(ray.exceptions.ActorDiedError):
            ray.get(ref, timeout=5)
        assert len(p.events()) == 1  # exactly the planned injection fired


def test_ray_kill_resolves_pending_refs(ray_start_local):
    """The direct ray.kill() path (LocalBackend.kill_actor → stop →
    resolve_pending) must also error out in-flight AND queued refs —
    deterministic via an entry event instead of a sleep."""
    import threading

    ray = ray_start_local
    started = threading.Event()

    @ray.remote
    class Slow:
        def nap(self):
            started.set()
            time.sleep(30)
            return "done"

    a = Slow.remote()
    ref = a.nap.remote()
    queued = a.nap.remote()  # sits in the queue behind the in-flight call
    assert started.wait(timeout=10), "nap must have started"
    ray.kill(a)
    with pytest.raises(ray.exceptions.ActorDiedError):
        ray.get(queued, timeout=5)
    with pytest.raises(ray.exceptions.ActorDiedError):
        ray.get(ref, timeout=5)


def test_call_after_kill_raises(ray_start_local):
    ray = ray_start_local

    @ray.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray.get(a.ping.remote()) == "pong"
    ray.kill(a)
    with pytest.raises(ray.exceptions.ActorDiedError):
        ray.get(a.ping.remote(), timeout=5)


def test_name_released_after_kill(ray_start_local):
    ray = ray_start_local

    @ray.remote
    class N:
        def who(self):
            return 1

    h1 = N.options(name="reusable").remote()
    ray.kill(h1)
    h2 = N.options(name="reusable").remote()  # must not raise "name taken"
    assert ray.get(h2.who.remote()) == 1


def test_method_num_returns(ray_start_local):
    ray = ray_start_local
    from ray_tpu import method

    @ray.remote
    class M:
        @method(num_returns=2)
        def two(self):
            return "a", "b"

    m = M.remote()
    r1, r2 = m.two.remote()
    assert ray.get([r1, r2]) == ["a", "b"]


def test_handle_pickles_with_method_metadata(ray_start_local):
    ray = ray_start_local
    from ray_tpu import method

    @ray.remote
    class M:
        @method(num_returns=2)
        def two(self):
            return 1, 2

    @ray.remote
    def use(h):
        a, b = h.two.remote()
        return ray.get([a, b])

    m = M.remote()
    assert ray.get(use.remote(m)) == [1, 2]


def test_failed_init_releases_name_and_errors_calls(ray_start_local):
    ray = ray_start_local

    @ray.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("bad init")

        def ping(self):
            return "pong"

    h = Broken.options(name="fragile").remote()  # must NOT raise (async create)
    # surfaces as ActorDiedError (init already failed) or the init exception
    # itself (call raced ahead of construction) — both are acceptable
    with pytest.raises((ray.exceptions.RayTpuError, RuntimeError)):
        ray.get(h.ping.remote(), timeout=5)

    @ray.remote
    class Fine:
        def ping(self):
            return "ok"

    h2 = Fine.options(name="fragile").remote()  # name released after init failure
    assert ray.get(h2.ping.remote()) == "ok"
