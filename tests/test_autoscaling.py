"""Closed-loop elasticity (PR 18): SLO-driven autoscaling with graceful
drain, scale-to-zero, durable scale decisions, and elastic cluster nodes.

Layers under test:

- unit: ReplicaScalingPolicy (pure function of signals + injected clock),
  collect_signals over synthetic metric samples, AutoscaleEngine's
  checkpoint-BEFORE-apply contract, NodeTier ownership records;
- cluster: the full loop — load raises the metric-derived target, the
  reconcile ticker grows the fleet, silence drains it back down through
  the DrainCoordinator (never a mid-request kill), reconcile never stalls
  on scaling, a SIGKILLed controller restores its DECIDED targets;
- chaos: a replica SIGKILLed while DRAINING fails its in-flight requests
  over typed; a node scale-down pre-spills primaries so spill adoption
  keeps them byte-identical after the raylet is gone;
- regression: an idle owner's pin-lease renewals ride a dedicated send
  (not the batched meta queue) and keep a primary pinned across many TTLs.
"""

import os
import threading
import time

import pytest


def _clock():
    t = {"v": 1000.0}

    def now():
        return t["v"]

    def advance(d):
        t["v"] += d

    return now, advance


def _ac(**kw):
    from ray_tpu.serve.deployment import AutoscalingConfig

    return AutoscalingConfig(**kw)


# --------------------------------------------------------------- unit: policy
def test_policy_step_load_converges_in_one_upscale_cooldown():
    from ray_tpu.autoscaling import DeploymentSignals, ReplicaScalingPolicy

    now, advance = _clock()
    p = ReplicaScalingPolicy(now=now)
    ac = _ac(min_replicas=1, max_replicas=8, target_ongoing_requests=2.0,
             upscale_delay_s=5.0, downscale_delay_s=10.0)
    # 12 ongoing over 2 replicas: jump straight to ceil(12/2)=6, not 3
    sig = DeploymentSignals(qps=20.0, ongoing=12.0)
    assert p.decide("d", ac, 2, 2, sig) == 6
    # still overloaded but inside the cooldown: hold
    assert p.decide("d", ac, 6, 2, sig) == 6
    # converged (avg == target is NOT overloaded): hold after the cooldown
    advance(5.0)
    assert p.decide("d", ac, 6, 6, sig) == 6


def test_policy_hysteresis_band_never_flaps():
    from ray_tpu.autoscaling import DeploymentSignals, ReplicaScalingPolicy

    now, advance = _clock()
    p = ReplicaScalingPolicy(now=now)
    ac = _ac(min_replicas=1, max_replicas=8, target_ongoing_requests=2.0,
             upscale_delay_s=1.0, downscale_delay_s=1.0)
    # avg 1.5 sits between target/2 (1.0) and target (2.0): nothing moves,
    # no matter how many cooldowns elapse
    sig = DeploymentSignals(qps=5.0, ongoing=9.0)
    for _ in range(5):
        assert p.decide("d", ac, 6, 6, sig) == 6
        advance(2.0)


def test_policy_scales_down_one_step_per_cooldown():
    from ray_tpu.autoscaling import DeploymentSignals, ReplicaScalingPolicy

    now, advance = _clock()
    p = ReplicaScalingPolicy(now=now)
    ac = _ac(min_replicas=1, max_replicas=8, target_ongoing_requests=2.0,
             upscale_delay_s=1.0, downscale_delay_s=10.0)
    sig = DeploymentSignals(qps=1.0, ongoing=1.0)  # avg stays < target/2
    assert p.decide("d", ac, 4, 4, sig) == 3
    # inside the down cooldown: hold (one step at a time, not a collapse)
    assert p.decide("d", ac, 3, 3, sig) == 3
    advance(10.0)
    assert p.decide("d", ac, 3, 3, sig) == 2
    # never below min_replicas
    advance(10.0)
    assert p.decide("d", ac, 2, 2, sig) == 1
    advance(10.0)
    assert p.decide("d", ac, 1, 1, sig) == 1


def test_policy_scale_to_zero_needs_full_quiet_window_then_wakes():
    from ray_tpu.autoscaling import DeploymentSignals, ReplicaScalingPolicy

    now, advance = _clock()
    p = ReplicaScalingPolicy(now=now)
    ac = _ac(min_replicas=0, max_replicas=4, target_ongoing_requests=2.0,
             upscale_delay_s=1.0, downscale_delay_s=10.0)
    quiet = DeploymentSignals()  # series never appeared: zero demand
    # silence starts the quiet clock but does NOT drop to zero yet
    assert p.decide("d", ac, 1, 1, quiet) == 1
    advance(9.0)
    assert p.decide("d", ac, 1, 1, quiet) == 1
    # a blip of traffic resets the quiet window
    assert p.decide("d", ac, 1, 1, DeploymentSignals(qps=2.0, ongoing=1.0)) == 1
    advance(9.0)
    assert p.decide("d", ac, 1, 1, quiet) == 1
    advance(10.0)
    assert p.decide("d", ac, 1, 1, quiet) == 0
    # arrivals against the empty fleet wake it immediately (zero_wake)
    assert p.decide("d", ac, 0, 0, DeploymentSignals(qps=3.0)) == 1
    # a min_replicas floor > 1 wakes to the floor, not to one replica
    ac2 = _ac(min_replicas=2, max_replicas=4)
    assert p.decide("e", ac2, 0, 0, DeploymentSignals(qps=3.0)) == 2


def test_policy_shed_rate_forces_an_upscale_step():
    from ray_tpu.autoscaling import DeploymentSignals, ReplicaScalingPolicy

    now, _ = _clock()
    p = ReplicaScalingPolicy(now=now)
    ac = _ac(min_replicas=1, max_replicas=8, target_ongoing_requests=2.0,
             upscale_delay_s=1.0, downscale_delay_s=10.0)
    # ongoing alone says "fine" (avg 0.5) but requests are being SHED:
    # the queue is refusing work, so capacity must grow anyway
    sig = DeploymentSignals(qps=50.0, ongoing=1.0, shed_rate=4.0)
    assert p.decide("d", ac, 2, 2, sig) == 3


def test_collect_signals_reads_only_the_deployments_series():
    from ray_tpu.autoscaling import collect_signals

    def sample(ts, requests, ongoing):
        return {
            "ts": ts,
            "series": [
                {
                    "name": "serve_requests_total", "kind": "counter",
                    "points": {
                        (("deployment", "d"),): requests,
                        (("deployment", "other"),): 9999.0,
                    },
                },
                {
                    "name": "serve_replica_ongoing", "kind": "gauge",
                    "points": {
                        (("deployment", "d"), ("replica", "a")): ongoing,
                        (("deployment", "other"), ("replica", "z")): 50.0,
                    },
                },
            ],
        }

    samples = [sample(100.0, 10.0, 3.0), sample(102.0, 20.0, 5.0)]
    sig = collect_signals(samples, "d")
    assert sig.qps == pytest.approx(5.0)     # (20-10)/2s, "other" excluded
    assert sig.ongoing == pytest.approx(5.0)  # newest gauge report
    assert sig.queue_wait_p90_ms is None      # series absent -> None
    assert sig.shed_rate is None
    # a deployment with no points at all reads as "no demand", not an error
    empty = collect_signals(samples, "ghost")
    assert empty.qps in (None, 0.0) and empty.ongoing is None


def test_collect_signals_first_ever_request_reads_as_arrivals():
    """A counter whose FIRST appearance is inside the window holds one
    constant level (1.0), so plain first→last rate is zero — but that one
    request IS the scale-from-zero wake signal and must read as qps > 0."""
    from ray_tpu.autoscaling import collect_signals

    def sample(ts, series):
        return {"ts": ts, "series": series}

    req = {
        "name": "serve_requests_total", "kind": "counter",
        "points": {(("deployment", "d"),): 1.0},
    }
    samples = [
        sample(100.0, []),            # window starts BEFORE any traffic
        sample(100.2, []),
        sample(100.4, [req]),         # the first request ever arrives...
        sample(100.6, [req]),         # ...and the level then sits constant
    ]
    sig = collect_signals(samples, "d")
    assert sig.qps is not None and sig.qps > 0
    # but a level that was already there at the window start is history,
    # not new arrivals: no phantom wake on a long-quiet deployment
    flat = [sample(100.0, [req]), sample(100.6, [req])]
    assert not collect_signals(flat, "d").qps


# --------------------------------------------------------------- unit: engine
class _StubPolicy:
    def __init__(self, out):
        self.out = out

    def decide(self, name, ac, current, running, sig):
        return self.out

    def forget(self, name):
        pass


def test_engine_checkpoint_failure_aborts_the_apply():
    from ray_tpu.autoscaling import AutoscaleEngine

    ac = _ac(min_replicas=1, max_replicas=8)
    applied = []

    def bad_checkpoint(targets):
        raise RuntimeError("durable KV down")

    eng = AutoscaleEngine(
        snapshot=lambda: [("d", ac, 1, 1)],
        apply=lambda ch: applied.append(dict(ch)),
        checkpoint=bad_checkpoint,
        fetch_samples=lambda: [],
        policy=_StubPolicy(3),
        interval_s=3600,
    )
    # durability before actuation: if the decision can't be made durable,
    # the fleet must NOT move (a restart would forget the scale-up)
    with pytest.raises(RuntimeError):
        eng.tick()
    assert applied == []
    assert eng.scale_events == 0


def test_engine_checkpoints_full_target_map_before_apply():
    from ray_tpu.autoscaling import AutoscaleEngine

    ac = _ac(min_replicas=1, max_replicas=8)
    order = []
    eng = AutoscaleEngine(
        snapshot=lambda: [("d", ac, 1, 1), ("plain", None, 2, 2)],
        apply=lambda ch: order.append(("apply", dict(ch))),
        checkpoint=lambda t: order.append(("ckpt", dict(t))),
        fetch_samples=lambda: [],
        policy=_StubPolicy(3),
        interval_s=3600,
    )
    assert eng.tick() == {"d": 3}
    # the checkpoint carries the FULL map (restore needs every deployment)
    # and lands strictly before the in-memory commit
    assert order == [("ckpt", {"d": 3, "plain": 2}), ("apply", {"d": 3})]
    assert eng.scale_events == 1 and eng.ticks == 1


def test_engine_no_change_means_no_checkpoint_write():
    from ray_tpu.autoscaling import AutoscaleEngine

    ac = _ac(min_replicas=1, max_replicas=8)
    order = []
    eng = AutoscaleEngine(
        snapshot=lambda: [("d", ac, 2, 2)],
        apply=lambda ch: order.append(("apply", dict(ch))),
        checkpoint=lambda t: order.append(("ckpt", dict(t))),
        fetch_samples=lambda: [],
        policy=_StubPolicy(2),  # decides the current target
        interval_s=3600,
    )
    assert eng.tick() == {}
    assert order == []


def test_engine_skips_metrics_fetch_without_autoscaled_deployments():
    from ray_tpu.autoscaling import AutoscaleEngine

    def boom():
        raise AssertionError("fetch must not run for fixed deployments")

    eng = AutoscaleEngine(
        snapshot=lambda: [("plain", None, 2, 2)],
        apply=lambda ch: None,
        fetch_samples=boom,
        interval_s=3600,
    )
    assert eng.tick() == {}


def test_node_tier_ownership_record_roundtrip():
    import json

    from ray_tpu.autoscaling import NodeTier
    from ray_tpu.autoscaling.engine import NODES_KEY, NODES_NS

    store = {}

    def kv(method, ns=None, key=None, value=None):
        if method == "kv_put":
            store[(ns, key)] = value
            return True
        if method == "kv_get":
            return store.get((ns, key))
        raise AssertionError(method)

    assert NodeTier.restore_owned(kv) == []
    kv("kv_put", ns=NODES_NS, key=NODES_KEY,
       value=json.dumps(["node-a", "node-b"]).encode())
    assert NodeTier.restore_owned(kv) == ["node-a", "node-b"]
    # corrupt record reads as empty, never raises into the caller
    kv("kv_put", ns=NODES_NS, key=NODES_KEY, value=b"{not json")
    assert NodeTier.restore_owned(kv) == []


# ------------------------------------------------------- cluster: closed loop
@pytest.fixture
def elastic_cluster():
    """Real cluster with fast metric/scaling clocks. Env vars reach the
    controller/replica/daemon processes (spawned after us); the direct
    ``_config`` mutation covers this driver process, whose singleton was
    built before the env override. Function-scoped on purpose: several
    tests in this file tear the global runtime down and re-init, which a
    module-scoped cluster cannot survive."""
    import ray_tpu
    from ray_tpu.core.config import _config

    env = {
        "RAY_TPU_METRICS_REPORT_INTERVAL_MS": "200",
        "RAY_TPU_SERVE_AUTOSCALE_INTERVAL_S": "0.25",
        "RAY_TPU_SERVE_AUTOSCALE_WINDOW_S": "6.0",
    }
    saved_env = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    fields = {
        "metrics_report_interval_ms": 200,
        "serve_autoscale_interval_s": 0.25,
        "serve_autoscale_window_s": 6.0,
    }
    saved_cfg = {k: getattr(_config, k) for k in fields}
    for k, v in fields.items():
        setattr(_config, k, v)
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    from ray_tpu import serve
    from ray_tpu.serve import api as serve_api

    serve_api._local.clear()  # no handles from an earlier cluster
    yield ray_tpu, serve
    try:
        serve.shutdown()
    except Exception:  # noqa: BLE001 - cluster already torn down
        serve_api._local.clear()
    ray_tpu.shutdown()
    for k, v in saved_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    for k, v in saved_cfg.items():
        setattr(_config, k, v)


def test_closed_loop_scales_up_under_load_and_drains_back(elastic_cluster):
    """Load -> metric-derived target rises -> fleet grows; silence ->
    surplus replicas retire through the DRAIN protocol (zero failed
    requests end to end); reconcile never stalls on the scaling path."""
    ray, serve = elastic_cluster
    from ray_tpu.core.config import _config

    @serve.deployment(
        name="Elastic", max_ongoing_requests=4,
        autoscaling_config=dict(
            min_replicas=1, max_replicas=3, target_ongoing_requests=1.0,
            upscale_delay_s=0.5, downscale_delay_s=1.5,
        ),
    )
    class Elastic:
        def __call__(self, x):
            time.sleep(0.25)
            return x * 2

    handle = serve.run(Elastic.bind())
    results, errors = [], []
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            try:
                results.append(ray.get(handle.remote(7), timeout=30))
            except Exception as e:  # noqa: BLE001 - collected for the assert
                errors.append(e)

    threads = [threading.Thread(target=pump, daemon=True) for _ in range(8)]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 45
        peak_target = peak_running = 1
        while time.time() < deadline:
            st = serve.status()["Elastic"]
            peak_target = max(peak_target, st["target"])
            peak_running = max(peak_running, st["running"])
            if peak_target >= 2 and peak_running >= 2:
                break
            time.sleep(0.3)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    assert peak_target >= 2, f"target never rose under load: {serve.status()}"
    assert peak_running >= 2, "the fleet never actually grew"
    assert not errors, f"scaling must not fail requests: {errors[:3]}"
    assert results and all(r == 14 for r in results)

    # silence: the engine walks the target back to min and the surplus
    # replicas retire through the drain coordinator
    deadline = time.time() + 60
    while time.time() < deadline:
        st = serve.status()["Elastic"]
        if st["target"] == 1 and st["running"] == 1 and not st["draining"]:
            break
        time.sleep(0.5)
    st = serve.status()
    assert st["Elastic"]["target"] == 1, st
    assert st["Elastic"]["running"] == 1, st
    ctl = st["_control"]
    assert ctl["autoscale_events"] >= 2       # at least one up + one down
    assert ctl["drained"] >= 1                # graceful retire, not a kill
    assert ctl["reconcile_ticks"] > 0 and ctl["autoscale_ticks"] > 0
    # the old _autoscale blocked reconcile up to 10s on a metrics fan-out;
    # the engine thread must keep every reconcile tick under the SLO
    assert ctl["max_reconcile_stall_s"] < _config.serve_reconcile_max_stall_s
    serve.delete("Elastic")


def test_scale_to_zero_cold_wake_records_cold_start(elastic_cluster):
    ray, serve = elastic_cluster

    @serve.deployment(
        name="Napper",
        autoscaling_config=dict(
            min_replicas=0, max_replicas=2, target_ongoing_requests=2.0,
            upscale_delay_s=0.3, downscale_delay_s=1.0,
        ),
    )
    def napper(x):
        return {"v": x + 1}

    handle = serve.run(napper)
    # min_replicas=0 deploys an EMPTY fleet: the first request is the wake
    assert serve.status()["Napper"]["running"] == 0
    assert ray.get(handle.remote(41), timeout=60) == {"v": 42}
    assert serve.status()["Napper"]["running"] >= 1

    # this driver's router measured the queued-against-empty-fleet time
    from ray_tpu.util import metrics as m

    cold = next((s for s in m.get_registry().collect()
                 if s["name"] == "serve_cold_start_ms"), None)
    assert cold is not None, "cold wake must observe serve_cold_start_ms"
    assert any(sum(v) > 0 for v in cold["points"].values()
               if isinstance(v, list))

    # silence returns it all the way to zero...
    deadline = time.time() + 45
    while time.time() < deadline:
        st = serve.status()["Napper"]
        if st["target"] == 0 and st["running"] == 0:
            break
        time.sleep(0.3)
    st = serve.status()["Napper"]
    assert st["target"] == 0 and st["running"] == 0, st
    # ...and it wakes again on the next request
    assert ray.get(handle.remote(1), timeout=60) == {"v": 2}
    serve.delete("Napper")


def test_controller_sigkill_mid_scale_restores_decided_target(elastic_cluster):
    """The engine checkpoints a decided target BEFORE actuating it, so a
    controller SIGKILLed mid-scale-up restores the decision (not the
    deploy-time floor) and resumes converging. The durability proof is the
    KV itself, read pre-kill: racing the restarted engine's first tick is
    unsound because a FRESH policy (no cooldown stamps) may legally take
    one immediate downscale step against the now-idle fleet."""
    import json

    ray, serve = elastic_cluster
    from ray_tpu.api import _global_worker
    from ray_tpu.serve import api as serve_api

    @serve.deployment(
        name="Durable", max_ongoing_requests=4,
        autoscaling_config=dict(
            min_replicas=1, max_replicas=3, target_ongoing_requests=1.0,
            upscale_delay_s=0.3, downscale_delay_s=3600.0,  # freeze downs
        ),
    )
    class Durable:
        def __call__(self, x):
            time.sleep(0.3)
            return x + 1

    handle = serve.run(Durable.bind())
    stop = threading.Event()
    errors = []

    def pump():
        while not stop.is_set():
            try:
                ray.get(handle.remote(1), timeout=30)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    # 6 pumps against target_ongoing=1.0: the step-up decision jumps to
    # ceil(ongoing/target) — drive until the decision hits max (3) so the
    # post-restart floor contrast below is unambiguous
    threads = [threading.Thread(target=pump, daemon=True) for _ in range(6)]
    for t in threads:
        t.start()
    decided = 1
    try:
        deadline = time.time() + 45
        while time.time() < deadline:
            decided = serve.status()["Durable"]["target"]
            if decided >= 3:
                break
            time.sleep(0.2)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
    assert decided == 3, "load never drove the target to max"
    assert not errors, errors[:3]

    # the decision is already durable: checkpoint precedes apply, so the
    # scale_targets KV records it the instant status() can show it
    core = _global_worker().backend.core

    def kv_get(ns, key):
        async def call():
            return await core.gcs.call("kv_get", ns=ns, key=key, timeout=30)

        return core.io.run(call(), timeout=60)

    blob = kv_get("serve", "scale_targets")
    ckpt = json.loads(blob.decode() if isinstance(blob, bytes) else blob)
    assert ckpt.get("Durable") == decided, f"checkpoint missing: {ckpt}"

    # SIGKILL the controller mid-convergence (its owned replicas die too)
    controller = ray.get_actor(serve_api.CONTROLLER_NAME)
    ray.kill(controller)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            ray.get_actor(serve_api.CONTROLLER_NAME)
            time.sleep(0.25)
        except Exception:  # noqa: BLE001 - controller gone
            break

    serve_api._local.clear()
    serve.start()
    # with zero load, a controller restoring only the deployment checkpoint
    # sits at the deploy floor (min_replicas=1) forever — reconverging to
    # >= 2 replicas is reachable ONLY through the restored scale_targets
    # overlay (the fresh policy may dip 3 -> 2 once, then downscale is
    # frozen for 3600 s, so >= 2 is the stable restored state)
    st = None
    deadline = time.time() + 90
    while time.time() < deadline:
        try:
            st = serve.status()["Durable"]
        except Exception:  # noqa: BLE001 - controller still booting
            time.sleep(0.5)
            continue
        if st["target"] >= 2 and st["running"] >= 2:
            break
        time.sleep(0.5)
    assert st is not None, "restarted controller never answered status()"
    assert st["target"] >= 2 and st["running"] >= 2, (
        f"fleet fell back to the deploy floor: {st}"
    )
    # the restored fleet serves traffic — retried: the first request can
    # still race a stale routing entry from the torn-down fleet (router
    # reports it dead, replacement lands next reconcile tick)
    h2 = serve.get_handle("Durable")
    got = None
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            got = ray.get(h2.remote(41), timeout=15)
            break
        except Exception:  # noqa: BLE001 - stale-entry race, retry
            time.sleep(0.5)
    assert got == 42, "restored fleet never answered"
    serve.delete("Durable")


def test_router_quorum_ejects_replica_and_reconcile_replaces_it(
        elastic_cluster):
    """One router's open breaker is local evidence (recorded only); a
    quorum of DISTINCT routers ejects the replica fleet-wide and the
    reconcile ticker starts a replacement."""
    ray, serve = elastic_cluster
    from ray_tpu.serve import api as serve_api
    from ray_tpu.serve.controller import _replica_key

    @serve.deployment(name="Quorum", num_replicas=2)
    def q(x):
        return x + 5

    handle = serve.run(q)
    assert ray.get(handle.remote(1), timeout=60) == 6
    controller = serve_api._local["controller"]
    table = ray.get(controller.routing_table.remote(-1), timeout=30)
    actors = table["deployments"]["Quorum"]
    assert len(actors) == 2
    victim = _replica_key(actors[0])

    # one router reporting twice is still ONE reporter: no ejection
    for _ in range(2):
        ray.get(controller.report_replica_state.remote(
            "Quorum", victim, "open", "router-a"), timeout=30)
    st = serve.status()["Quorum"]
    assert st["running"] == 2
    assert st["circuit"].get(victim.hex()) == "open"

    # a second distinct router completes the quorum: ejected + drained
    ray.get(controller.report_replica_state.remote(
        "Quorum", victim, "open", "router-b"), timeout=30)
    replaced = False
    deadline = time.time() + 45
    while time.time() < deadline:
        t2 = ray.get(controller.routing_table.remote(-1), timeout=30)
        keys = {_replica_key(a) for a in t2["deployments"]["Quorum"]}
        if victim not in keys and len(keys) == 2:
            replaced = True
            break
        time.sleep(0.3)
    assert replaced, "ejected replica was not replaced by a fresh one"
    assert ray.get(handle.remote(2), timeout=60) == 7
    assert serve.status()["_control"]["drained"] >= 1
    serve.delete("Quorum")


# ------------------------------------------------- chaos: SIGKILL mid-drain
@pytest.fixture
def chaos_cluster():
    import ray_tpu
    from ray_tpu.serve import api as serve_api
    from ray_tpu.testing import chaos

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=4, num_tpus=0)
    from ray_tpu import serve

    serve_api._local.clear()  # no handles from an earlier cluster
    yield ray_tpu, serve
    chaos.deactivate()
    try:
        serve.shutdown()
    except Exception:  # noqa: BLE001 - cluster already torn down
        serve_api._local.clear()
    ray_tpu.shutdown()


def test_chaos_sigkill_draining_replica_fails_over_typed(chaos_cluster):
    """A replica SIGKILLed the moment it enters DRAINING (before its
    in-flight requests finish) must resolve those requests through the
    router failover plane — retried to a survivor or a TYPED error, never
    an untyped crash or a hang. The plan must show the ``replica.drain``
    fire happened in the controller process."""
    ray, serve = chaos_cluster
    import ray_tpu.exceptions as rexc
    from ray_tpu.testing import chaos

    plan = chaos.plan(seed=18).kill_draining_replica(match="Shrink")
    # push to the ALREADY-running daemons so the controller (spawned by a
    # raylet after this) inherits the plan env
    assert chaos.activate(plan) >= 1

    @serve.deployment(name="Shrink", num_replicas=2, max_ongoing_requests=8)
    class Shrink:
        def __call__(self, x):
            time.sleep(1.0)
            return x * 3

    handle = serve.run(Shrink.bind())
    # warm both replicas so the routing table is fully populated
    assert sorted(ray.get([handle.remote(i) for i in range(2)],
                          timeout=90)) == [0, 3]

    results, errors = {}, []

    def call(i):
        try:
            results[i] = ray.get(handle.remote(i), timeout=60)
        except Exception as e:  # noqa: BLE001 - asserted typed below
            errors.append(e)

    threads = [threading.Thread(target=call, args=(i,), daemon=True)
               for i in range(6)]
    for t in threads:
        t.start()
    time.sleep(0.4)  # let the batch dispatch across BOTH replicas
    # shrink to one replica: the surplus replica enters DRAINING with
    # requests in flight and the chaos plan SIGKILLs it right there
    serve.run(Shrink.options(num_replicas=1).bind())
    for t in threads:
        t.join(timeout=120)

    assert len(results) + len(errors) == 6, "a request hung"
    for e in errors:
        assert isinstance(e, rexc.RayTpuError), f"untyped failure: {e!r}"
    for i, v in results.items():
        assert v == i * 3, f"failover corrupted request {i}: {v}"
    # the kill really happened, mid-drain, in the controller (not here)
    events = [e for e in plan.events() if e["point"] == "replica.drain"]
    assert events, "replica.drain never fired"
    assert events[0]["action"] == "kill"
    assert events[0]["pid"] != os.getpid()
    chaos.deactivate()

    deadline = time.time() + 45
    while time.time() < deadline:
        if serve.status()["Shrink"]["running"] == 1:
            break
        time.sleep(0.3)
    assert serve.status()["Shrink"]["running"] == 1
    serve.delete("Shrink")


# ------------------------------------------------- cluster: elastic node tier
@pytest.fixture
def tier_cluster():
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    ray_tpu.shutdown()
    c = Cluster(head_node_args={"num_cpus": 1})
    ray_tpu.init(address=c.address)
    yield ray_tpu, c
    ray_tpu.shutdown()
    c.shutdown()


def test_node_scale_down_pre_spills_primaries_byte_identical(tier_cluster):
    """Demand launches a node; idleness retires it THROUGH the drain path
    (``drain_node`` pre-spills every in-memory primary), so an object whose
    only copy lived on the leaving node is still readable byte-identical
    afterwards via spill adoption. The tier's durable ownership record
    tracks the fleet both ways."""
    ray, c = tier_cluster
    from ray_tpu.api import _global_worker
    from ray_tpu.autoscaler import LocalNodeProvider
    from ray_tpu.autoscaling import NodeTier

    core = _global_worker().backend.core

    def gcs_call(method, **k):
        async def call():
            return await core.gcs.call(method, timeout=30, **k)

        return core.io.run(call(), timeout=60)

    blob = b"elasticity" * 131072  # ~1.3 MB: a real shm primary

    provider = LocalNodeProvider(c.address, c.session)
    tier = NodeTier(
        provider, gcs_call, min_nodes=0, max_nodes=1,
        upscale_delay_s=0.3, idle_timeout_s=2.0, poll_interval_s=0.3,
        node_resources={"CPU": 2}, kv_call=gcs_call,
    )
    tier.start()
    try:
        # the 1-CPU head can't fit CPU:2 -> queued demand grows the fleet
        @ray.remote(num_cpus=2)
        def make_blob():
            return b"elasticity" * 131072

        ref = make_blob.remote()
        ready, _ = ray.wait([ref], timeout=120)
        assert ready, "demand-driven scale-up never ran the task"
        nodes = provider.non_terminated_nodes()
        assert len(nodes) == 1 and tier.scale_ups >= 1
        # ownership record is durable while the node is up
        assert NodeTier.restore_owned(gcs_call) == sorted(nodes)

        # idle -> graceful drain -> terminate (do NOT touch ref before:
        # its only in-memory copy must be on the node that leaves)
        deadline = time.time() + 60
        while provider.non_terminated_nodes() and time.time() < deadline:
            time.sleep(0.5)
        assert provider.non_terminated_nodes() == []
        assert tier.scale_downs >= 1
        assert any("scale-down" in e for e in tier.events)

        assert ray.get(ref, timeout=60) == blob
        assert NodeTier.restore_owned(gcs_call) == []
    finally:
        tier.stop()
        provider.shutdown()


# --------------------------------------- regression: idle-owner pin renewal
@pytest.fixture
def pin_cluster():
    import ray_tpu
    from ray_tpu.core.config import _config

    env = {
        "RAY_TPU_OBJECT_PIN_TTL_S": "1.0",
        "RAY_TPU_OBJECT_PIN_RENEW_INTERVAL_S": "0.25",
    }
    saved_env = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    saved_cfg = (_config.object_pin_ttl_s, _config.object_pin_renew_interval_s)
    _config.object_pin_ttl_s = 1.0
    _config.object_pin_renew_interval_s = 0.25
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, num_tpus=0)
    yield ray_tpu
    ray_tpu.shutdown()
    for k, v in saved_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    _config.object_pin_ttl_s, _config.object_pin_renew_interval_s = saved_cfg


def test_idle_owner_pin_lease_outlives_many_ttls(pin_cluster):
    """Renewals from a COMPLETELY idle owner must keep a primary's pin
    lease alive. They used to ride the batched owner-metadata queue, which
    only flushes when other traffic wakes it and dropped its payload
    silently on a send error — an idle driver's primary could quietly
    become evictable. The dedicated renewal send (with its own retry)
    closes that: after several full TTLs of doing NOTHING, the object is
    still pinned in the raylet."""
    ray = pin_cluster
    # big enough to bypass the inline path and land in the shm store as a
    # pinned PRIMARY (> max_direct_call_object_size)
    payload = b"pinned" * 50_000
    ray.put(b"warmup")  # ensure the store/meta planes are up
    ref = ray.put(payload)
    time.sleep(3.5)  # idle across >3 TTL windows; renewals are the only RPC

    from ray_tpu.api import _global_worker

    core = _global_worker().backend.core

    async def stats():
        return await core.raylet.call("object_stats", timeout=30)

    st = core.io.run(stats(), timeout=60)
    assert st["pinned_bytes"] > 0, (
        f"pin lease expired on an idle owner: {st}"
    )
    assert ray.get(ref, timeout=30) == payload
